"""Runtime-hazard passes: observed dispatch/cache pathologies.

The static source passes predict hazards; these passes *observe* them
after a workload ran:

* MXL401 — jit-cache key blowup via ``engine.cache_info()``: an op with
  many cache entries whose keys differ only in one or two attr values is
  recompiling per value; the attr should ride the dynamic-scalar path
  (``scalar_attrs``) or be hoisted to a constant.
* MXL305 — silent CompiledStep degradation: a training loop that asked
  for the one-dispatch compiled step but is actually running per-op
  eager dispatches (non-hybridizable forward, optimizer without a fused
  program, ...).  The finding carries the recorded fallback reason.
* MXL306 / MXL307 — telemetry-plane hazards (``analyze_telemetry``):
  retraces AFTER warm-up (each finding carries the attributed cause —
  the exact attr/shape/dtype diff from the retrace event) and a
  prefetch pipeline that stalls the consumer too often (input-bound
  training masquerading as slow compute).
"""
from __future__ import annotations

from typing import List

from .findings import Finding

__all__ = ["analyze_cache", "analyze_compiled_steps",
           "analyze_telemetry", "analyze_compile_cache",
           "analyze_memory", "analyze_parallel", "analyze_elasticity",
           "analyze_health", "analyze_serving"]


def analyze_cache(threshold: int = 8) -> List[Finding]:
    """Flag ops whose jit-cache entry count exceeds ``threshold``.

    Shape/dtype-driven re-specialization also multiplies entries (that is
    healthy and unavoidable), so the message names the varying attrs when
    the blowup is attributable to attr values — the actionable case.
    """
    from .. import engine
    info = engine.cache_info()
    findings: List[Finding] = []
    for name, sigs in sorted(info["ops"].items()):
        if len(sigs) <= threshold:
            continue
        # which attr names take multiple distinct values across entries?
        values_by_attr = {}
        for sig in sigs:
            try:
                items = list(sig)
            except TypeError:
                items = []
            for kv in items:
                if isinstance(kv, tuple) and len(kv) == 2:
                    values_by_attr.setdefault(kv[0], set()).add(kv[1])
        varying = sorted(a for a, vals in values_by_attr.items()
                         if len(vals) > 1)
        detail = (f"; attr(s) {', '.join(varying)} vary across entries — "
                  "candidates for scalar_attrs") if varying else \
            " (distinct attr signatures; check call sites)"
        findings.append(Finding(
            "MXL401", f"op {name!r} holds {len(sigs)} compiled cache "
            f"entries (threshold {threshold}){detail}",
            f"cache:{name}"))
    return findings


def analyze_compiled_steps() -> List[Finding]:
    """One MXL305 finding per CompiledStep that silently fell back to
    the eager per-op path this process (``compiled_step.
    fallback_reports()``).  The explicit ``MXTPU_COMPILED_STEP=0``
    escape hatch never reports — only surprising degradations do."""
    from ..gluon import compiled_step as _cs
    return [
        Finding("MXL305",
                f"compiled train step {name!r} silently fell back to "
                f"the eager per-op path: {reason}",
                f"step:{name}")
        for name, reason in _cs.fallback_reports()]


def analyze_compile_cache() -> List[Finding]:
    """MXL402 — corrupt entries in the persistent compile cache
    (``MXTPU_COMPILE_CACHE_DIR``; quiet when the tier is disabled).

    Dispatch-time loads are corruption-TOLERANT (a bad entry falls back
    to a fresh compile), which is the right production behavior but
    the wrong CI behavior: silent fallback turns a corrupted cache
    volume into an invisible cold-start regression.  This pass — and
    ``tools/mxcache.py verify``, which it mirrors — fails the
    ``--self-check`` gate loudly instead.  Fingerprint-stale entries
    (another jax/jaxlib/platform wrote them) are well-formed and not
    flagged.
    """
    from ..engine import persist
    if not persist.enabled():
        return []
    return [
        Finding("MXL402",
                f"persistent compile-cache entry {r['file']!r} is "
                f"corrupt ({r.get('error')}); dispatch would silently "
                "fall back to a fresh compile — delete it or run "
                "tools/mxcache.py prune",
                f"persist:{r['file']}")
        for r in persist.verify() if not r["ok"]]


def analyze_memory(large_buffer_bytes: int = 8 << 20,
                   replicated_bytes: int = 64 << 20) -> List[Finding]:
    """Memory-observatory hazards observed by THIS process's run
    (``telemetry.memory`` — free when nothing was harvested, so the
    ``--self-check`` CI gate stays quiet in a fresh process).

    * MXL308 — a harvested program takes an input of at least
      ``large_buffer_bytes`` whose identical aval also flows OUT (the
      updated-buffer signature: weights in, new weights out) without
      that input being in the donate tuple: the step double-buffers the
      tensor in HBM for no reason.  The check consumes output avals for
      donated inputs first, so a properly donated twin never shadows a
      non-donated one.
    * MXL309 — a registered param layout (``DataParallelTrainer``
      registers its post-placement tree) holds a tensor of at least
      ``replicated_bytes`` fully replicated across a multi-device
      mesh — the exact misuse a sharding rule (``param_sharding``)
      exists to prevent; N copies of an embedding table is the
      canonical case.
    * MXL310 — ``MXTPU_ZERO_STAGE>=1`` is set, yet a registered
      optimizer-state layout on a dp>1 mesh is fully REPLICATED: the
      trainer was ineligible for the sharded update (no fused rule,
      tensor-parallel params, 2bit compression, ...) and silently
      fell back to stage 0 — dp copies of Adam's m/v burning HBM the
      env var promised to shard (docs/zero.md).
    """
    from ..telemetry import memory as mem
    from collections import Counter
    findings: List[Finding] = []
    for name, rec in sorted(mem.programs().items()):
        out_avals = rec.get("out_avals")
        if not out_avals:
            continue            # persist reloads carry no output avals
        outs = Counter(tuple(a) for a in out_avals)
        donated = set(rec.get("donated_idx") or ())
        in_avals = rec.get("in_avals") or ()
        # donated inputs claim their output twins first
        for j in donated:
            if j < len(in_avals) and outs.get(tuple(in_avals[j]), 0):
                outs[tuple(in_avals[j])] -= 1
        for j, aval in enumerate(in_avals):
            if j in donated:
                continue
            nb = mem._aval_entry_bytes(aval)
            if nb < large_buffer_bytes:
                continue
            key = tuple(aval)
            if outs.get(key, 0) > 0:
                outs[key] -= 1
                shape = aval[0] if len(aval) == 2 else ()
                findings.append(Finding(
                    "MXL308",
                    f"program {name!r}: input #{j} "
                    f"(shape {list(shape)}, {nb} bytes) flows out "
                    "updated but is not in the donate tuple — the "
                    "step holds old AND new copies in HBM; add it to "
                    "donate_argnums / the fused plan's donate tuple",
                    f"memory:{name}"))
    for tname, tree in sorted(mem.param_trees().items()):
        if tree.get("mesh_size", 1) <= 1:
            continue
        for row in tree.get("params", ()):
            if row["nbytes"] >= replicated_bytes and row["replicated"]:
                findings.append(Finding(
                    "MXL309",
                    f"{tname}: param {row['name']!r} "
                    f"({row['nbytes']} bytes, shape {row['shape']}) is "
                    f"fully replicated across a "
                    f"{tree['mesh_size']}-device mesh — "
                    f"{tree['mesh_size']}x the HBM for one tensor; "
                    "give it a param_sharding rule",
                    f"memory:{tname}:{row['name']}"))
    # the planner's rule-level coverage audit (MXL313) rides along:
    # analyze_memory is the HBM-hazard surface and a mis-covered plan
    # is exactly an HBM hazard with a named culprit
    findings.extend(analyze_parallel())
    from .. import envs
    if int(envs.get("MXTPU_ZERO_STAGE")) >= 1:
        for tname, tree in sorted(mem.opt_state_trees().items()):
            if tree.get("dp_size", 1) <= 1 or not tree.get("leaves"):
                continue
            if tree.get("zero_stage", 0) >= 1:
                continue
            if all(r["replicated"] for r in tree["leaves"]):
                findings.append(Finding(
                    "MXL310",
                    f"{tname}: MXTPU_ZERO_STAGE="
                    f"{int(envs.get('MXTPU_ZERO_STAGE'))} is set but "
                    f"all {tree['count']} optimizer-state leaves "
                    f"({tree['total_bytes']} bytes) are fully "
                    f"replicated across the {tree['dp_size']}-member "
                    "dp axis — the trainer fell back to stage 0 "
                    "(no fused rule / TP params / 2bit compression?); "
                    "each member burns the full state HBM the env "
                    "var promised to shard (docs/zero.md)",
                    f"memory:{tname}:opt_state"))
    return findings


def analyze_parallel(big_bytes: int = 64 << 20,
                     plan=None, named_shapes=None,
                     owner: str = "plan") -> List[Finding]:
    """MXL313 — sharding-plan coverage audit (docs/parallelism.md,
    "Coverage lint"): the rule-level successor of the MXL309/310
    symptom checks.  For every registered live plan
    (``parallel.planner.plans()`` — trainers/servers register at
    setup), or an explicit ``(plan, named_shapes)`` pair (the
    ``tools/mxplan.py lint`` entry point):

    * a trainable param matched by NO rule — it replicates silently,
      which is the failure mode a declarative plan exists to kill
      (default rule sets end with an explicit catch-all);
    * an UNREACHABLE rule: some param's name matches its regex, but an
      earlier rule claims every such param — dead weight that usually
      means a rule-ordering bug;
    * a tensor of at least ``big_bytes`` the resolved plan fully
      replicates on a >1-device mesh — the MXL309/310 symptom, now
      with the responsible rule ATTRIBUTED in the message.

    Free in a fresh process (empty registry), so the ``--self-check``
    CI gate stays quiet.
    """
    from ..parallel import planner as _planner
    entries = {}
    if plan is not None:
        entries[str(owner)] = {
            "plan": plan, "named_shapes": list(named_shapes or ()),
            "dtype_bytes": 4}
    else:
        entries = _planner.plans()
    findings: List[Finding] = []
    for name, rec in sorted(entries.items()):
        p = rec["plan"]
        cov = p.coverage(rec["named_shapes"],
                         dtype_bytes=rec.get("dtype_bytes", 4),
                         big_bytes=big_bytes)
        for pname, shape, nbytes in cov["uncovered"]:
            findings.append(Finding(
                "MXL313",
                f"{name}: param {pname!r} (shape {list(shape)}, "
                f"{nbytes} bytes) matches NO plan rule and replicates "
                "silently; add a rule (or end the rule list with an "
                "explicit catch-all) so every layout decision is "
                "deliberate",
                f"plan:{name}:{pname}"))
        for idx, pattern, first in cov["shadowed"]:
            findings.append(Finding(
                "MXL313",
                f"{name}: rule #{idx} ({pattern!r}) is unreachable — "
                f"every param it matches is claimed by an earlier "
                f"rule (first: #{first} "
                f"{p.rules[first][0]!r}); reorder or delete it",
                f"plan:{name}:rule{idx}"))
        for pname, nbytes, idx in cov["replicated_big"]:
            culprit = "no rule matched" if idx is None else \
                f"rule #{idx} ({p.rules[idx][0]!r} -> " \
                f"{p.rules[idx][1]})"
            findings.append(Finding(
                "MXL313",
                f"{name}: param {pname!r} ({nbytes} bytes) is fully "
                f"replicated across the {p.n_devices}-device mesh by "
                f"the resolved plan ({culprit}) — "
                f"{p.n_devices}x the HBM for one tensor; give it a "
                "sharding rule",
                f"plan:{name}:{pname}"))
        for pname, shape, idx in cov["demoted"]:
            findings.append(Finding(
                "MXL313",
                f"{name}: rule #{idx} ({p.rules[idx][0]!r} -> "
                f"{p.rules[idx][1]}) wants a sharding param "
                f"{pname!r} (shape {list(shape)}) cannot honor — a "
                "sharded dim does not divide the axis fan-out, so the "
                "param silently replicated instead; pad the dim or "
                "fix the rule",
                f"plan:{name}:{pname}"))
    return findings


def analyze_elasticity(min_steps: int = 100) -> List[Finding]:
    """Elastic-plane hazards (docs/elasticity.md).

    * MXL501 (runtime form of the source pass) — at least ``min_steps``
      train steps ran in THIS process and no
      ``elastic.CheckpointManager`` was ever constructed: a preemption
      or post-donation dispatch failure at step N loses all N steps.
      Reads ``telemetry.current_step()``, so a fresh CI process (the
      ``--self-check`` gate) yields nothing.
    * MXL502 (the CI face of ``tools/mxckpt.py verify``) — integrity of
      every checkpoint directory this process saved into, plus
      ``MXTPU_CHECKPOINT_DIR`` when set: a committed checkpoint whose
      manifest or shard hashes fail is an ERROR (restore would refuse
      it — the retention window is silently thinner than configured); a
      torn ``.tmp-step-*`` dir is only a WARNING (a crash artifact or
      an in-flight write; ``mxckpt.py prune`` clears it).
    * MXL503 — a COMPLETED live resize (``elastic.resize.resizes()``)
      that broke its contract: the first post-swap step paid
      ``fresh_compiles > 0`` (the pre-warm promised the swap a ready
      executable and did not deliver — downtime silently grew by a
      compile), or the drain committed an OLDER step than the trainer
      had reached (a mid-resize crash-heal would then lose committed
      training work).  Quiet in a fresh process (empty registry), and
      a healthy resize whose probe has not fired yet
      (``post_swap_fresh_compiles`` still ``None``) reports nothing.
    * MXL504 — guardian-plane incidents left open (docs/elasticity.md,
      "Guardian & chaos soak"): a retained ``hang_suspected`` event
      never answered by a recovery (no later ``recovery`` event, and
      no ``hang_resolved`` that either recovered or resolved clean) —
      the watchdog saw a dispatch die and nobody healed the owner; a
      ``preempted`` event whose drain committed NOTHING (no manager
      in scope — the preemption lost the run the drain exists to
      save); or a chaos-soak artifact (``elastic.chaos.artifacts()``)
      with violated invariants — the last one at ERROR severity, so
      ``tools/mxsoak.py run --self-check`` and a post-soak
      ``self_check()`` gate fail loudly.
    * MXL505 — silent-corruption incidents left open (docs/
      elasticity.md, "Integrity sentry"): a retained
      ``corruption_suspected`` event never answered by a
      ``corruption_resolved``/``device_quarantined``/``recovery``,
      or a scrub-found-corrupt checkpoint still standing as a
      committed restore target (ERROR severity — ``tools/mxsdc.py
      audit`` is the standalone face).
    """
    from .. import envs, telemetry
    from ..elastic import manager as _mgr
    from .findings import Severity
    findings: List[Finding] = []
    steps = telemetry.current_step()
    if steps >= min_steps and _mgr.managers_created() == 0:
        findings.append(Finding(
            "MXL501", f"{steps} train steps ran in this process and no "
            "elastic.CheckpointManager was ever constructed — a "
            "preemption or post-donation dispatch failure now loses "
            "the whole run; see docs/elasticity.md",
            "elastic:no-manager"))
    dirs = set(_mgr.known_dirs())
    env_dir = str(envs.get("MXTPU_CHECKPOINT_DIR") or "").strip()
    if env_dir:
        dirs.add(env_dir)
    for d in sorted(dirs):
        for row in _mgr.verify_dir(d):
            if row["ok"]:
                continue
            if row.get("partial"):
                findings.append(Finding(
                    "MXL502", f"torn checkpoint write {row['path']!r} "
                    "(crash artifact or in-flight writer); "
                    "tools/mxckpt.py prune clears it",
                    f"ckpt:{row['path']}",
                    severity=Severity.WARNING))
            else:
                findings.append(Finding(
                    "MXL502", f"checkpoint step {row['step']} at "
                    f"{row['path']!r} fails integrity: "
                    f"{'; '.join(row['errors'])[:300]} — restore "
                    "would refuse it, so the retention window is "
                    "thinner than configured; keep more steps or "
                    "delete the corrupt dir",
                    f"ckpt:{row['path']}"))
    from ..elastic import resize as _resize
    for n, rec in enumerate(_resize.resizes()):
        where = (f"{rec.get('kind')} "
                 f"{rec.get('mesh_from') or rec.get('slots_from')} -> "
                 f"{rec.get('mesh_to') or rec.get('slots_to')}")
        fresh = rec.get("post_swap_fresh_compiles")
        if fresh:
            findings.append(Finding(
                "MXL503",
                f"live resize #{n} ({where}) paid {fresh} fresh "
                f"compile(s) on its first post-swap step — the "
                "pre-warm contract is broken and the measured "
                "downtime silently excludes a compile; check the "
                "persist tier / prepare_resize coverage of every "
                "dispatched variant (docs/elasticity.md, 'Live "
                "resize')",
                f"resize:{n}"))
        drain = rec.get("drain_step")
        committed = rec.get("committed_step")
        if drain is not None and committed is not None and \
                int(committed) < int(drain):
            findings.append(Finding(
                "MXL503",
                f"live resize #{n} ({where}) drained at trainer step "
                f"{drain} but committed checkpoint step {committed} — "
                "a mid-resize crash-heal would lose "
                f"{int(drain) - int(committed)} committed step(s); "
                "the drain must land ON the boundary, not behind it",
                f"resize:{n}"))

    # MXL504 — guardian-plane incidents left open.  An event sequence
    # answers a hang_suspected when a recovery lands AFTER it, or its
    # own hang_resolved reports recovered/clean; a preempted event is
    # answered by the committed step its drain recorded.
    recovery_seqs = [e["seq"] for e in telemetry.events("recovery")]
    resolved = telemetry.events("hang_resolved")
    for ev in telemetry.events("hang_suspected"):
        answered = any(s > ev["seq"] for s in recovery_seqs) or any(
            r["seq"] > ev["seq"] and r.get("owner") == ev.get("owner")
            and (r.get("recovered") or not r.get("error"))
            for r in resolved)
        if not answered:
            findings.append(Finding(
                "MXL504",
                f"hang_suspected on {ev.get('owner')!r} "
                f"({ev.get('what')}, {ev.get('seconds')}s in flight) "
                "was never answered by a recovery — the owner is "
                "likely still poisoned or the dispatch is still "
                "wedged; see the event's stack dump and "
                "MXTPU_WATCHDOG_ACTION=recover (docs/elasticity.md)",
                f"guardian:hang:{ev['seq']}"))
    for ev in telemetry.events("preempted"):
        if ev.get("ok") and ev.get("committed_step") is None:
            findings.append(Finding(
                "MXL504",
                "a preemption drained with NO committed checkpoint "
                "(no CheckpointManager in the guard's scope) — the "
                "drain protocol saved nothing and the run is lost on "
                "exit; attach a manager to the PreemptionGuard",
                f"guardian:preempt:{ev['seq']}"))
    from ..elastic import chaos as _chaos
    for n, art in enumerate(_chaos.artifacts()):
        if art.get("ok"):
            continue
        broken = sorted(v.get("invariant", "?")
                        for v in art.get("violations", ()))
        findings.append(Finding(
            "MXL504",
            f"chaos soak #{n} (seed {art.get('seed')}, "
            f"{art.get('steps')} steps) VIOLATED invariant(s) "
            f"{broken}: the composed fault surface does not recover "
            "cleanly — replay with tools/mxsoak.py run --seed "
            f"{art.get('seed')} and fix before shipping",
            f"soak:{n}", severity=Severity.ERROR))

    # MXL505 — silent-corruption incidents left open (docs/
    # elasticity.md, "Integrity sentry").  A corruption_suspected is
    # ANSWERED by a later corruption_resolved / device_quarantined /
    # recovery event (the rollback and quarantine ladders both emit
    # one); an unanswered one means the run detected corruption and
    # kept training on it — exactly the "trains wrong silently"
    # failure the sentry exists to kill.  The scrub leg: a checkpoint
    # the scrubber found corrupt that STILL stands as a committed
    # restore target (quarantine=False, or the rename failed) is an
    # ERROR — the next recovery would either refuse it (retention
    # silently thinner) or, with verify=False, restore garbage.
    answer_seqs = [e["seq"] for kind in
                   ("corruption_resolved", "device_quarantined",
                    "recovery")
                   for e in telemetry.events(kind)]
    for ev in telemetry.events("corruption_suspected"):
        if any(s > ev["seq"] for s in answer_seqs):
            continue
        findings.append(Finding(
            "MXL505",
            f"corruption_suspected on {ev.get('where')!r} "
            f"({ev.get('row')} fingerprints, suspect device(s) "
            f"{ev.get('suspects')}) was never answered by a "
            "rollback, quarantine, or recovery — the run kept "
            "training on suspect state; set "
            "MXTPU_INTEGRITY_ACTION=rollback|quarantine (and attach "
            "owner.health_manager), or resolve and restart",
            f"integrity:suspected:{ev['seq']}"))
    from ..elastic import integrity as _integrity
    for n, rec in enumerate(_integrity.scrub_log()):
        if rec.get("ok") or rec.get("quarantined"):
            continue
        step = rec.get("step")
        if step is None or step not in _mgr._committed_steps(
                rec.get("dir", "")):
            continue        # gone or already quarantined out of band
        findings.append(Finding(
            "MXL505",
            f"checkpoint step {step} at {rec.get('dir')!r} failed its "
            "scrub but still stands as a committed restore target — "
            "the next recovery would refuse it (or restore garbage "
            "with verify=False); quarantine it (scrub(quarantine="
            "True)) or delete the dir",
            f"integrity:scrub:{n}", severity=Severity.ERROR))
    return findings


def analyze_health() -> List[Finding]:
    """MXL312 — the runtime sibling of the MXL311 source rule
    (docs/observability.md, Training health).

    Reads the health plane's per-owner sentinels: an owner whose run
    recorded anomalies (nonfinite gradients, loss spikes, grad-norm
    explosions, update-ratio collapse) gets one WARNING finding
    carrying the anomaly census and the last verdict, so a CI
    ``--self-check`` run AFTER an in-process workload fails visibly
    instead of letting a diverging configuration land.  Free in a
    fresh process (no sentinels — the CI gate stays quiet).
    """
    from ..telemetry import health as _health
    findings: List[Finding] = []
    for where, sent in sorted(_health.sentinels().items()):
        snap = sent.snapshot()
        anomalies = snap.get("anomalies") or []
        if not anomalies:
            continue
        kinds = {}
        for a in anomalies:
            kinds[a.get("anomaly")] = kinds.get(a.get("anomaly"), 0) + 1
        census = ", ".join(f"{k} x{v}" for k, v in sorted(kinds.items()))
        v = snap.get("last_verdict")
        verdict = f"; last verdict: {v['kind']} at step " \
                  f"{v.get('step')}" if v else ""
        findings.append(Finding(
            "MXL312",
            f"{where}: {len(anomalies)} training-health anomalies "
            f"over {snap.get('samples', 0)} samples ({census})"
            f"{verdict} — the run's numerics are suspect; see the "
            "health_anomaly events (tools/mxhealth.py) and consider "
            "MXTPU_HEALTH_ACTION=skip|rollback",
            f"health:{where}"))
    return findings


def analyze_serving() -> List[Finding]:
    """MXL601 runtime twin (docs/serving.md): steady-state compile
    accounting per serving bucket.

    Every live ``serving.Server`` brackets each dispatch of an
    already-compiled bucket variant with ``engine.compile_counts()``;
    a nonzero steady-state miss or fresh-compile count means the
    bucket's programs kept compiling AFTER they existed — an aval or
    shape leaked into the decode path (the exact hazard fixed bucket
    shapes exist to prevent).  Free in a fresh process (no servers —
    the ``--self-check`` CI gate stays quiet).
    """
    from ..serving import servers
    findings: List[Finding] = []
    for srv in servers():
        for bucket, stats in sorted(srv.stats()["buckets"].items()):
            steady = stats.get("steady_dispatches", 0)
            misses = stats.get("steady_misses", 0)
            fresh = stats.get("steady_fresh_compiles", 0)
            if not steady or not (misses or fresh):
                continue
            findings.append(Finding(
                "MXL601",
                f"{srv.name}: bucket {bucket} compiled "
                f"{misses} cache miss(es) / {fresh} fresh compile(s) "
                f"across {steady} steady-state dispatches — decode "
                "must reuse ONE program per bucket; something varies "
                "a shape/dtype per step (see docs/serving.md, "
                "'Zero-retrace contract')",
                f"serving:{srv.name}:{bucket}"))
    return findings


def analyze_telemetry(warmup_steps: int = 2,
                      stall_threshold: float = 0.25) -> List[Finding]:
    """Telemetry-plane hazards observed by THIS process's run.

    * MXL306 — a ``retrace`` event recorded after ``warmup_steps``
      train steps: steady-state training should compile NOTHING; the
      finding carries the attributed cause (which attr/shape/dtype
      changed, old -> new) so the fix is named, not hunted.
    * MXL307 — the prefetch pipeline's stall ratio (batches the
      consumer had to wait for / batches consumed) exceeded
      ``stall_threshold``: the step time is input-bound and the fix is
      more workers / deeper prefetch / faster decode, not kernel work.

    Both read the telemetry plane (events ring + metric counters), so
    the pass is free when nothing was recorded — a fresh process (the
    ``--self-check`` CI gate) yields no findings.
    """
    from .. import telemetry
    findings: List[Finding] = []
    for ev in telemetry.events("retrace"):
        # an event's step field reads "completed steps when emitted":
        # a retrace DURING step N+1 carries step N (note_step advances
        # at step END), so the first post-warm-up step's retraces
        # arrive stamped warmup_steps — strict < keeps them
        step = ev.get("step", 0)
        if step < warmup_steps:
            continue
        changed = ", ".join(
            f"{k}: {v[0]} -> {v[1]}"
            for k, v in sorted(ev.get("changed", {}).items())) \
            or "unknown"
        findings.append(Finding(
            "MXL306",
            f"op {ev.get('op')!r} retraced during step {step + 1} "
            f"(after {warmup_steps} warm-up steps); "
            f"cause={ev.get('cause')}: {changed}",
            f"retrace:{ev.get('op')}"))
    ratio = telemetry.prefetch_stall_ratio()
    if ratio > stall_threshold:
        snap = telemetry.snapshot()["counters"]
        findings.append(Finding(
            "MXL307",
            f"prefetch stall ratio {ratio:.2f} exceeds "
            f"{stall_threshold:.2f} "
            f"({int(snap.get('mxtpu_prefetch_stalls_total', 0))} of "
            f"{int(snap.get('mxtpu_dataloader_batches_total', 0))} "
            "batches found the queue dry) — training is input-bound; "
            "raise num_workers/prefetch or move decode off the "
            "consumer",
            "prefetch:stalls"))
    return findings
