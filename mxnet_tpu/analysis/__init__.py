"""``mxnet_tpu.analysis``: static analysis over the rebuild's three
contract surfaces (the Relay lesson — an IR pays for itself through the
passes you run over it):

* **graph passes** (MXL1xx) over the Symbol IR — cycles, duplicate
  names, dead nodes, and a shape/dtype contract validator that
  abstract-evaluates every node via ``jax.eval_shape`` (no device time);
* **registry passes** (MXL2xx) over every ``OpDef`` — arity /
  scalar-attr / namespace-symmetry / cache-key contracts;
* **source passes** (MXL3xx, Python ``ast``) — host-sync and
  retrace-storm hazards in user code before any device time is spent;
* **runtime pass** (MXL4xx) — observed jit-cache key blowup via
  ``engine.cache_info()``.

CLI: ``tools/mxlint.py`` (exits nonzero on error-severity findings, so
it gates CI).  Rules are documented in ``docs/static_analysis.md``.
"""
from .findings import (Finding, Severity, RULES, rule_severity,
                       filter_findings, format_findings)
from .graph_passes import analyze_symbol, analyze_graph_json, node_path
from .registry_passes import analyze_registry, analyze_opdef
from .source_passes import analyze_source, analyze_file, analyze_paths
from .runtime import (analyze_cache, analyze_compiled_steps,
                      analyze_telemetry, analyze_compile_cache,
                      analyze_memory, analyze_parallel,
                      analyze_elasticity, analyze_health,
                      analyze_serving)
from . import sanitizer
from .sanitizer import analyze_sanitizer
from . import wire_passes
from .wire_passes import analyze_wire, wire_report
from .corpus import builtin_symbols, traced_model_symbols, model_corpus

__all__ = [
    "Finding", "Severity", "RULES", "rule_severity", "filter_findings",
    "format_findings",
    "analyze_symbol", "analyze_graph_json", "node_path",
    "analyze_registry", "analyze_opdef",
    "analyze_source", "analyze_file", "analyze_paths",
    "analyze_cache", "analyze_compiled_steps", "analyze_telemetry",
    "analyze_compile_cache", "analyze_memory", "analyze_parallel",
    "analyze_elasticity", "analyze_health", "analyze_serving",
    "sanitizer", "analyze_sanitizer",
    "wire_passes", "analyze_wire", "wire_report",
    "builtin_symbols", "traced_model_symbols", "model_corpus",
    "self_check",
]


def self_check(full: bool = False, check_shapes: bool = True):
    """Run the registry passes over every registered op and the graph
    passes over the shipped model corpus.

    Returns ``(findings, ok)`` where ``ok`` means zero error-severity
    findings — the CI gate ``tools/mxlint.py --self-check`` enforces.
    """
    findings = list(analyze_registry())
    for name, sym, shapes in model_corpus(full=full):
        findings.extend(analyze_symbol(sym, shapes=shapes,
                                       check_shapes=check_shapes,
                                       name=name))
    # telemetry runtime pass (MXL306/307): free in a fresh CI process
    # (nothing recorded), but a self_check run AFTER a workload in the
    # same process surfaces steady-state retraces and prefetch stalls
    findings.extend(analyze_telemetry())
    # persistent compile-cache integrity (MXL402): a corrupted cache
    # dir must fail CI loudly, not surface as silent fresh compiles at
    # dispatch time (quiet when MXTPU_COMPILE_CACHE_DIR is unset)
    findings.extend(analyze_compile_cache())
    # memory-observatory pass (MXL308/309, and the planner's MXL313
    # coverage audit riding inside analyze_memory): quiet in a fresh
    # CI process; after an in-process workload it surfaces non-donated
    # updated buffers, large replicated tensors, and mis-covered plans
    findings.extend(analyze_memory())
    # elasticity pass (MXL501 runtime form / MXL502): quiet in a fresh
    # process; after an in-process workload it surfaces long
    # unprotected runs and corrupt/torn checkpoints this process wrote
    findings.extend(analyze_elasticity())
    # training-health pass (MXL312, the runtime sibling of MXL311):
    # quiet in a fresh process; after an in-process workload it
    # surfaces recorded numerics anomalies and the last verdict
    findings.extend(analyze_health())
    # serving pass (MXL601 runtime twin): quiet in a fresh process;
    # after in-process serving traffic it surfaces buckets that kept
    # compiling in steady state (the zero-retrace contract)
    findings.extend(analyze_serving())
    # sanitizer pass (MXL701-706, mxsan): quiet in a fresh process
    # (nothing armed, nothing recorded); after a sanitizer-armed run
    # it surfaces use-after-donate, lock-order cycles, and the rest
    # of the MXL7xx family — a sanitizer-armed soak that trips one
    # fails this gate
    findings.extend(analyze_sanitizer())
    # wire pass (MXL801-804, mxwire): quiet in a fresh process (no
    # step variants registered); after an in-process workload it walks
    # every registered fused-step jaxpr and checks the wire contracts
    # — declared leg precision, the ZeRO-2 reduce-scatter shape,
    # sampling gates on stats rows, static-vs-observatory bytes
    findings.extend(analyze_wire())
    ok = not any(f.severity == Severity.ERROR for f in findings)
    return findings, ok
