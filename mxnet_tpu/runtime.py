"""Runtime feature detection (parity: ``python/mxnet/runtime.py`` over
``src/libinfo.cc`` — SURVEY.md §5 "Config / flag system").

``Features()`` reports this build's capability matrix with the
reference's feature names (CUDA off, TPU/PJRT/PALLAS on, ...), so
feature-gated user code ports unchanged.
"""
from __future__ import annotations

from collections import OrderedDict

__all__ = ["Feature", "Features", "feature_list"]


class Feature:
    def __init__(self, name, enabled):
        self.name = name
        self.enabled = enabled

    def __repr__(self):
        return f"[{'✔' if self.enabled else '✖'} {self.name}]"


def _detect():
    feats = OrderedDict()

    def add(name, enabled):
        feats[name] = Feature(name, bool(enabled))

    try:
        import jax
        has_jax = True
    except ImportError:
        has_jax = False
    tpu = False
    if has_jax:
        try:
            devs = jax.devices()
            tpu = bool(devs) and devs[0].platform != "cpu"
        except Exception:
            tpu = False
    add("TPU", tpu)
    add("PJRT", has_jax)
    add("PALLAS", has_jax)
    add("DIST", has_jax)
    add("DIST_KVSTORE", True)
    add("INT64_TENSOR_SIZE", True)
    add("F16C", True)           # bf16/fp16 compute via XLA
    add("OPENCV", _has("cv2"))
    add("ORBAX", _has("orbax.checkpoint"))
    # reference features that are off in the TPU build — recorded
    # explicitly so `is_enabled('CUDA')` answers honestly
    for off in ("CUDA", "CUDNN", "NCCL", "CUDA_RTC", "TENSORRT",
                "MKLDNN", "OPENMP", "SSE", "CAFFE", "PROFILER_NVTX"):
        add(off, False)
    add("SIGNAL_HANDLER", True)
    add("PROFILER", True)
    return feats


def _has(mod):
    try:
        __import__(mod)
        return True
    except ImportError:
        return False


class Features(OrderedDict):
    """Check with ``mx.runtime.Features().is_enabled('TPU')``."""

    instance = None

    def __new__(cls):
        if cls.instance is None:
            cls.instance = super().__new__(cls)
            OrderedDict.__init__(cls.instance, _detect())
        return cls.instance

    def __init__(self):
        pass

    def __repr__(self):
        return str(list(self.values()))

    def is_enabled(self, feature_name: str) -> bool:
        feature_name = feature_name.upper()
        if feature_name not in self:
            raise RuntimeError(f"Feature {feature_name!r} does not exist")
        return self[feature_name].enabled


def feature_list():
    return list(Features().values())
