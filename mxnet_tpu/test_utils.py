"""Test utilities (parity: python/mxnet/test_utils.py — SURVEY.md §2.5).

Load-bearing for the whole suite, as in the reference: tolerance tables,
``assert_almost_equal``, ``check_numeric_gradient`` (finite differences, the
universal backward oracle), ``check_consistency`` (same op on two contexts),
``default_context``, random array helpers.
"""
from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from .context import Context, cpu, current_context

__all__ = ["default_context", "set_default_context", "assert_almost_equal",
           "almost_equal", "rand_ndarray", "rand_shape_nd",
           "check_numeric_gradient", "check_consistency", "same"]

_default = [None]

# per-dtype (rtol, atol), mirroring the reference's tolerance table
_TOLS = {
    np.dtype("float16"): (1e-2, 1e-2),
    np.dtype("bfloat16") if hasattr(np, "bfloat16") else np.dtype("float16"):
        (1e-2, 1e-2),
    np.dtype("float32"): (1e-4, 1e-5),
    np.dtype("float64"): (1e-6, 1e-7),
}


def default_context() -> Context:
    return _default[0] if _default[0] is not None else current_context()


def set_default_context(ctx: Context):
    _default[0] = ctx


def _tol(*dtypes):
    rtol, atol = 0.0, 0.0
    for d in dtypes:
        r, a = _TOLS.get(np.dtype(d), (1e-4, 1e-5))
        rtol, atol = max(rtol, r), max(atol, a)
    return rtol, atol


def _np(x):
    from .ndarray.ndarray import NDArray
    return x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)


def same(a, b):
    return np.array_equal(_np(a), _np(b))


def almost_equal(a, b, rtol=None, atol=None):
    a, b = _np(a), _np(b)
    r, t = _tol(a.dtype, b.dtype)
    return np.allclose(a, b, rtol=rtol or r, atol=atol or t)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b")):
    a_np, b_np = _np(a).astype("f8"), _np(b).astype("f8")
    r, t = _tol(_np(a).dtype, _np(b).dtype)
    np.testing.assert_allclose(a_np, b_np, rtol=rtol if rtol is not None
                               else r, atol=atol if atol is not None else t,
                               err_msg=f"{names[0]} != {names[1]}")


def rand_shape_nd(ndim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=ndim))


def rand_ndarray(shape, ctx=None, dtype="float32", scale=1.0):
    from .ndarray.ndarray import array
    data = (np.random.uniform(-scale, scale, size=shape)).astype(dtype)
    return array(data, ctx=ctx or default_context(), dtype=dtype)


def check_numeric_gradient(f: Callable, inputs, grads=None, eps=1e-3,
                           rtol=1e-2, atol=1e-3):
    """Finite-difference check: f takes/returns NDArrays; scalar output.

    Compares autograd gradients of ``sum(f(*inputs))`` against central
    differences — the reference's universal backward oracle.
    """
    from . import autograd
    from .ndarray.ndarray import array
    from .ndarray import sum as nd_sum

    inputs = list(inputs)
    for x in inputs:
        if x._grad is None:
            x.attach_grad()
    with autograd.record():
        out = f(*inputs)
        loss = nd_sum(out)
    loss.backward()
    analytic = [x.grad.asnumpy().copy() for x in inputs]

    for xi, x in enumerate(inputs):
        x_np = x.asnumpy().astype("f8")
        num = np.zeros_like(x_np)
        flat = x_np.reshape(-1)
        num_flat = num.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + eps
            x[:] = array(x_np.astype(x.dtype.name))
            fp = nd_sum(f(*inputs)).asscalar()
            flat[i] = orig - eps
            x[:] = array(x_np.astype(x.dtype.name))
            fm = nd_sum(f(*inputs)).asscalar()
            flat[i] = orig
            x[:] = array(x_np.astype(x.dtype.name))
            num_flat[i] = (fp - fm) / (2 * eps)
        np.testing.assert_allclose(analytic[xi], num, rtol=rtol, atol=atol,
                                   err_msg=f"gradient mismatch on input {xi}")


def check_consistency(f: Callable, inputs_np, ctx_list=None, rtol=None,
                      atol=None):
    """Run ``f`` on each context and require identical outputs.

    Parity: the reference's ``check_consistency`` (CPU vs GPU vs fp16);
    here: cpu vs tpu (or any ctx list).
    """
    from .ndarray.ndarray import array
    ctx_list = ctx_list or [cpu(0)]
    results = []
    for ctx in ctx_list:
        args = [array(a, ctx=ctx) for a in inputs_np]
        out = f(*args)
        outs = out if isinstance(out, (list, tuple)) else [out]
        results.append([o.asnumpy() for o in outs])
    ref = results[0]
    for got, ctx in zip(results[1:], ctx_list[1:]):
        for r, g in zip(ref, got):
            rt, at = _tol(r.dtype, g.dtype)
            np.testing.assert_allclose(
                g, r, rtol=rtol or rt, atol=atol or at,
                err_msg=f"inconsistent result on {ctx}")
    return results
