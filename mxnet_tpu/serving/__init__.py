"""``mxnet_tpu.serving``: the production inference serving plane
(ROADMAP item 4 — the "millions of users" leg).

Whole-program AOT compilation to FIXED shapes is the regime TPUs
reward (arXiv:1810.09868), and the compile-once/serve-forever
deployment story follows Relay's ahead-of-time philosophy
(arXiv:1810.00952).  This package turns the model zoo's
prefill/decode seams into that story:

* :mod:`~.kvcache` — preallocated per-slot K/V pages as DONATED carry
  state: every decode dispatch updates the caches in place and
  round-trips the buffers, with the PR 2/3 poison/recover protocol;
* :mod:`~.scheduler` — continuous batching over fixed
  ``(slots, prompt_len)`` buckets: admits and evicts swap slot
  contents and an active-mask input, NEVER shapes, so steady state
  retraces nothing;
* :mod:`~.server` — ``Server``: one compiled prefill + one compiled
  decode program per bucket (plus scan-bulked ``decode_multi``),
  greedy/temperature/top-k sampling with the CachedOp fold_in RNG
  scheme, ``save_signature``/``warm_start`` through the PR 5
  persistent tier (a fresh process serves its first token with 0
  fresh compiles), and the serving telemetry (tokens/sec, TTFT,
  per-request latency, occupancy, queue depth,
  ``request_evicted``/``slot_oom`` retained events).

See docs/serving.md for the bucket anatomy, a scheduler walkthrough,
the warm-start workflow, and the telemetry field reference.
"""
from .kvcache import KVCachePool
from .scheduler import Bucket, BucketScheduler, Request
from .server import Server, servers

__all__ = ["KVCachePool", "Bucket", "BucketScheduler", "Request",
           "Server", "servers"]
