"""KV-cache plane: preallocated per-slot K/V pages as DONATED carry
state (docs/serving.md).

A bucket's caches are one NDArray pair per transformer layer, shaped
``(slots, cache_len, kv_heads, head_dim)`` — slot ``j`` is request
``j``'s page.  Every decode dispatch donates the whole pool to the
compiled program (the PR 2/3 donation protocol): the executable writes
each active slot's new K/V in place and returns the successor buffers,
so a decode step never doubles cache HBM.  ``adopt()`` swaps the
successors in; a dispatch that fails AFTER the donation consumed the
buffers latches ``poisoned`` (the pool holds dead arrays) and
``reset()`` — driven by ``Server.recover()`` — rebuilds zeroed pages.

Slot lifecycle is content-swap only: admission scatters a freshly
prefilled page into slot ``j`` (one ``lax.dynamic_update_slice`` per
layer inside the admit program), eviction just drops the slot's
active-mask bit on the host.  Shapes never change, so steady state
retraces NOTHING (docs/serving.md, "Bucket anatomy").
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from ..base import MXNetError

__all__ = ["KVCachePool"]


class KVCachePool:
    """Per-bucket preallocated K/V pages for ``slots`` concurrent
    requests over ``lm``'s layers.

    Args:
      lm: a ``models.LlamaForCausalLM`` (anything with ``init_cache``).
      slots: concurrent requests the pool holds (the bucket batch dim).
      cache_len: positions per slot (bucket prompt length + the
        server's max new tokens).
      ctx: device context for the pages.
      dtype: cache dtype (float; ``bfloat16`` halves page HBM and
        decode bandwidth — ``init_cache`` validates).
      sharding: optional ``jax.sharding.NamedSharding`` for the pages
        — the sharding planner's decode spec (``ShardingPlan.decode``,
        typically the slot dim over ``dp``).  Applied after EVERY page
        build (construction AND :meth:`reset`), so a recovery can
        never silently drop the planned layout.
    """

    def __init__(self, lm, slots: int, cache_len: int, ctx=None,
                 dtype: str = "float32", sharding=None):
        if slots < 1 or cache_len < 1:
            raise MXNetError(
                f"KVCachePool needs slots >= 1 and cache_len >= 1, got "
                f"{slots}/{cache_len}")
        self._lm = lm
        self.slots = int(slots)
        self.cache_len = int(cache_len)
        self.ctx = ctx
        self.dtype = str(dtype)
        self.sharding = sharding
        self.poisoned: Optional[str] = None
        self._pairs: List[Tuple] = self._build_pages()

    def _build_pages(self):
        pairs = self._lm.init_cache(
            self.slots, self.cache_len, ctx=self.ctx, dtype=self.dtype)
        if self.sharding is not None:
            import jax
            for k, v in pairs:
                k._set_data(jax.device_put(k._data, self.sharding))
                v._set_data(jax.device_put(v._data, self.sharding))
        return pairs

    @property
    def num_layers(self) -> int:
        return len(self._pairs)

    def pairs(self):
        """The live per-layer ``(K, V)`` NDArray pairs."""
        return list(self._pairs)

    def flat(self) -> list:
        """Flat jax buffers ``[k0, v0, k1, v1, ...]`` in donate order —
        exactly the slice of the dispatch argument list the donate
        tuple names."""
        return [s._data for pair in self._pairs for s in pair]

    def nbytes(self) -> int:
        return sum(int(s._data.nbytes) for pair in self._pairs
                   for s in pair)

    def adopt(self, new_flat):
        """Swap the post-dispatch successor buffers in (the donated
        predecessors are already dead)."""
        if len(new_flat) != 2 * len(self._pairs):
            raise MXNetError(
                f"adopt: expected {2 * len(self._pairs)} cache buffers, "
                f"got {len(new_flat)}")
        for i, (k, v) in enumerate(self._pairs):
            k._set_data(new_flat[2 * i])
            v._set_data(new_flat[2 * i + 1])

    def poison(self, error: str):
        """Latch the post-donation-failure state: the pages were
        consumed by a dispatch that died, so nothing here is
        dispatchable until :meth:`reset`."""
        self.poisoned = error

    def consumed(self) -> bool:
        """Did a dispatch actually consume the pages?  (Distinguishes
        post-donation failures — dead buffers — from pre-dispatch
        trace/compile errors that left everything alive.)"""
        return any(
            getattr(s._data, "is_deleted", lambda: False)()
            for pair in self._pairs for s in pair)

    def reset(self):
        """Rebuild zeroed pages and clear the poison latch (the
        recovery half of the donation protocol — every resident
        request must be re-prefilled by the caller)."""
        self._pairs = self._build_pages()
        self.poisoned = None
