"""``Server``: the engine + persist integration of the serving plane.

One compiled PREFILL program and one compiled DECODE program per
``(slots, prompt_len)`` bucket (plus ``decode_multi(K)`` lax.scan
variants), all dispatched through ``engine.invoke_compiled`` with the
bucket's KV-cache pool DONATED:

* **admit** — prefill one right-padded prompt at batch 1, scatter the
  resulting K/V page into the pool at the assigned slot
  (``lax.dynamic_update_slice`` per layer), and sample the first token
  at the prompt's own last position — ONE dispatch per admission;
* **decode** — every active slot advances one token in lockstep at its
  OWN absolute position (per-slot rope offsets / cache scatter /
  validity mask ride as dynamic inputs), the sampler picks
  greedy-or-temperature per slot, and the whole pool round-trips
  through donation — ONE dispatch per step, zero retraces across any
  admit/evict sequence (shapes never change);
* **decode_multi(K)** — K decode steps as one dispatch (``lax.scan``
  with the pool as carry, like ``step_multi``): one host sync per K
  tokens instead of per token.

Sampling is greedy at ``temperature == 0`` and softmax sampling with
optional server-wide top-k truncation otherwise; the RNG threads the
CachedOp fold_in scheme — one base key INPUT per dispatch (drawn from
the global stream, so keys never retrace) folded per inner step and
per slot.

``save_signature``/``warm_start`` extend the PR 5 AOT warm-start
machinery to serving: a fresh process precompiles every recorded
bucket variant through ``engine.aot_compile`` + the persistent tier
and serves its FIRST token with 0 fresh compiles.

Failure protocol (docs/elasticity.md applied to serving): the engine's
bounded transient retry covers pre-donation hiccups; a dispatch that
fails AFTER consuming the donated pool poisons the bucket, and
``recover()`` rebuilds zeroed pages and requeues every resident
request (prompts are host-owned, so they replay from scratch).
"""
from __future__ import annotations

import hashlib
import itertools
import json
import os
import threading
import time
import weakref
from typing import Dict, List, Optional

import numpy as np

from ..base import MXNetError
from .kvcache import KVCachePool
from .scheduler import ACTIVE, BucketScheduler, Request

__all__ = ["Server", "servers"]

_uid = itertools.count(1)

# live-server registry read by mxlint's serving runtime pass
# (``analysis.analyze_serving`` — MXL601's runtime twin)
_reg_lock = threading.Lock()
_servers: "weakref.WeakValueDictionary[int, Server]" = \
    weakref.WeakValueDictionary()


def servers() -> List["Server"]:
    with _reg_lock:
        return [s for s in _servers.values()]


def _reset_registry():
    """Test hook."""
    with _reg_lock:
        _servers.clear()


def _default_buckets():
    from .. import envs
    slots = int(envs.get("MXTPU_SERVING_SLOTS"))
    lens = [int(x) for x in
            str(envs.get("MXTPU_SERVING_BUCKETS")).split(",") if x.strip()]
    return [(slots, n) for n in lens]


class Server:
    """Continuously batched serving over a ``LlamaForCausalLM``-shaped
    model (anything exposing ``init_cache``/``prefill``/``decode_step``
    — the model-zoo decoder contract).

    Args:
      lm: initialized causal LM.
      buckets: ``[(slots, prompt_len), ...]`` shape classes (defaults
        from ``MXTPU_SERVING_SLOTS`` x ``MXTPU_SERVING_BUCKETS``).
      max_new_tokens: per-request generation cap (sizes the cache
        pages: ``cache_len = prompt_len + max_new_tokens``); defaults
        to ``MXTPU_SERVING_MAX_NEW_TOKENS``.
      top_k: server-wide top-k truncation for sampled requests (shapes
        the compiled sampler; 0 = full softmax).
      eos_id: stop token (None = run to the token budget).
      ctx: device context; default current.
      cache_dtype: KV page dtype (``bfloat16`` halves page HBM).
      max_queue: wait-queue bound (``MXTPU_SERVING_MAX_QUEUE``).
    """

    def __init__(self, lm, buckets=None, max_new_tokens: int = None,
                 top_k: int = 0, eos_id: Optional[int] = None,
                 ctx=None, cache_dtype: str = "float32",
                 max_queue: Optional[int] = None, plan=None):
        from .. import envs
        from ..context import current_context
        from ..parallel import planner as _planner
        self.lm = lm
        self.ctx = ctx or current_context()
        # the sharding planner's serving leg (docs/parallelism.md):
        # plan.decode is the KV-page / decode-batch partition spec on
        # the plan's named mesh — pinned into the struct hash and the
        # warm-start manifest, and APPLIED to the pools/params when it
        # actually shards (>1 device on the named axes)
        if plan is not None and \
                not isinstance(plan, _planner.ShardingPlan):
            raise MXNetError(
                f"plan= must be a parallel.ShardingPlan, got "
                f"{type(plan).__name__}")
        self.plan = plan
        self._decode_sharding = None
        self._repl_sharding = None
        self._placed_params = None
        if plan is not None and plan.decode_shards():
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            mesh = plan.build_mesh()
            self._decode_sharding = NamedSharding(mesh,
                                                  P(*plan.decode))
            self._repl_sharding = NamedSharding(mesh, P())
        if max_new_tokens is None:
            max_new_tokens = int(envs.get("MXTPU_SERVING_MAX_NEW_TOKENS"))
        if max_queue is None:
            max_queue = int(envs.get("MXTPU_SERVING_MAX_QUEUE"))
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = None if eos_id is None else int(eos_id)
        self.cache_dtype = str(cache_dtype)
        vocab = int(lm.model.vocab_size)
        self._kk = min(int(top_k), vocab) if top_k else 0
        self.sched = BucketScheduler(buckets or _default_buckets(),
                                     self.max_new_tokens, max_queue)
        try:
            self._param_nds = [p.data(self.ctx)
                               for p in lm.collect_params().values()]
        except Exception as e:
            raise MXNetError(
                "Server needs an initialized model (run initialize() "
                f"and one forward first): {e!r}") from e
        self.name = f"serving_{lm.name}_{next(_uid)}"
        if self._decode_sharding is not None:
            # the slot dim is the decode spec's leading entry: every
            # bucket's slot count must divide its device fan-out, or
            # the planned layout is unbuildable — reject NAMING the
            # spec instead of letting XLA pad silently
            fan = plan.decode_fanout()
            for b in self.sched.buckets:
                if fan > 1 and b.slots % fan:
                    raise MXNetError(
                        f"plan decode spec {plan.decode} shards the "
                        f"slot dim {fan}-way but bucket "
                        f"{b.slots}x{b.prompt_len} has {b.slots} "
                        "slot(s); pick slot counts divisible by the "
                        "decode axis size")
            import jax
            self._placed_params = [
                jax.device_put(p._data, self._repl_sharding)
                for p in self._param_nds]
        self._pools: Dict[tuple, KVCachePool] = {}
        for b in self.sched.buckets:
            self._pools[b.key] = KVCachePool(
                lm, b.slots, b.cache_len, ctx=self.ctx,
                dtype=self.cache_dtype,
                sharding=self._decode_sharding)
        if plan is not None:
            # the planner registry (MXL313 coverage audit + mxplan):
            # the serving leg registers its resolved param tree too
            from ..parallel import planner as _pl
            _pl.note_plan(
                f"serving:{lm.name}", plan,
                [(p.name, tuple(int(x) for x in p.data(self.ctx).shape))
                 for p in lm.collect_params().values()])
        self._pure_cache: Dict[str, callable] = {}
        self._variants: Dict[str, dict] = {}   # suffix -> manifest row
        self._warmed: set = set()              # suffixes dispatched
        self._bucket_stats: Dict[tuple, dict] = {
            b.key: {"steady_dispatches": 0, "tokens": 0,
                    "steady_misses": 0, "steady_fresh_compiles": 0}
            for b in self.sched.buckets}
        self._poisoned: Optional[str] = None
        self.warm_started = False
        self._persist_pinned = False
        self._struct_hash = self._compute_struct_hash()
        self._persist_base = f"serving_{lm.name}_{self._struct_hash}"
        with _reg_lock:
            _servers[id(self)] = self

    # -- identity ---------------------------------------------------------
    def _compute_struct_hash(self, buckets=None) -> str:
        """Structural identity over model/bucket/sampler config.
        ``buckets``: optional ``(slots, prompt_len, cache_len)`` rows
        to hash INSTEAD of the live ones — the resize pre-warm keys
        the target configuration's persist identities while the old
        buckets still serve."""
        rows = buckets if buckets is not None else \
            [(b.slots, b.prompt_len, b.cache_len)
             for b in self.sched.buckets]
        parts = (
            tuple((tuple(p.data(self.ctx).shape),
                   str(p.data(self.ctx).dtype))
                  for p in self.lm.collect_params().values()),
            tuple(sorted(tuple(r) for r in rows)),
            self._kk, self.cache_dtype, self.max_new_tokens,
            int(self.lm.model.vocab_size)) + (
                # the plan pin: decode sharding is baked into the
                # compiled programs' input layouts; appended only when
                # a plan exists so pre-planner hashes (and persisted
                # executables) still serve
                (self.plan.struct_hash(),)
                if self.plan is not None else ())
        return hashlib.sha256(repr(parts).encode()).hexdigest()[:16]

    # -- public API -------------------------------------------------------
    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               temperature: float = 0.0,
               eos_id: Optional[int] = None,
               ttl_ms: Optional[float] = None) -> Request:
        """Queue one generation request; admission happens at the next
        :meth:`step`.  Raises ``MXNetError`` when no bucket fits the
        prompt or the queue is full (both recorded as retained
        ``slot_oom`` events).

        ``ttl_ms`` arms the overload policy (docs/serving.md,
        "Overload policy"): when the ESTIMATED queue wait — queue
        depth x the rolling per-token service rate from the decode
        histograms — already exceeds the deadline, the request is SHED
        here (state ``shed``, retained ``shed`` event,
        ``mxtpu_requests_shed_total``, and an ``MXNetError`` the
        caller turns into a fast 429) instead of growing the queue a
        request that can only expire in it."""
        from .. import telemetry
        mnt = self.max_new_tokens if max_new_tokens is None \
            else min(int(max_new_tokens), self.max_new_tokens)
        req = Request(prompt, mnt, temperature=temperature,
                      eos_id=self.eos_id if eos_id is None else eos_id,
                      ttl_ms=ttl_ms)
        if req.deadline is not None:
            est = self.estimate_queue_wait()
            budget = req.deadline - time.perf_counter()
            if est is not None and est > budget:
                from .scheduler import SHED
                req.state = SHED
                req.evict_reason = "shed"
                telemetry.counter(
                    "mxtpu_requests_shed_total",
                    "requests shed at enqueue by the overload policy"
                    ).inc()
                telemetry.record_event(
                    "shed", server=self.name, request=req.id,
                    prompt_len=req.prompt_len, ttl_ms=req.ttl_ms,
                    est_wait_s=round(est, 4),
                    queue_depth=self.sched.queue_depth())
                raise MXNetError(
                    f"request shed: estimated queue wait {est:.3f}s "
                    f"exceeds the {req.ttl_ms:g}ms deadline (queue "
                    f"depth {self.sched.queue_depth()}); retry with "
                    "backoff, raise ttl_ms, or scale the plane "
                    "(docs/serving.md, 'Overload policy')")
        try:
            self.sched.enqueue(req)
        except MXNetError as e:
            telemetry.record_event(
                "slot_oom", server=self.name, request=req.id,
                prompt_len=req.prompt_len,
                queue_depth=self.sched.queue_depth(),
                reason=str(e)[:200])
            raise
        telemetry.counter("mxtpu_serving_requests_total",
                          "requests submitted to the serving plane"
                          ).inc()
        self._update_gauges()
        return req

    # -- overload policy (docs/serving.md, "Overload policy") -------------
    def estimate_queue_wait(self) -> Optional[float]:
        """Expected seconds a request submitted NOW waits before its
        slot frees up: queue depth x tokens-per-request x the rolling
        per-token service rate, spread over the plane's slots.  The
        rate comes from the histograms the plane already keeps
        (decode wall seconds / tokens generated); ``None`` before any
        decode history exists — an un-warmed plane never sheds."""
        from .. import telemetry
        q = self.sched.queue_depth()
        if q == 0 and self.sched.occupancy() < 1.0:
            return 0.0
        dh = telemetry.histogram(
            "mxtpu_serving_decode_seconds",
            "one decode dispatch wall clock (s)").summary()
        tokens = telemetry.counter(
            "mxtpu_serving_tokens_total",
            "tokens generated by the serving plane").value
        if not dh["count"] or tokens <= 0:
            return None
        per_token_s = dh["sum"] / tokens
        slots = sum(b.slots for b in self.sched.buckets) or 1
        # every queued request ahead needs ~max_new_tokens service
        # slots-widths of decode wall time before a slot frees
        waves = (q + slots) / slots
        return waves * self.max_new_tokens * per_token_s

    def _expire_deadlines(self) -> int:
        """Evict every live request whose deadline passed (queue AND
        slots — the scheduler's existing evict path does both), with
        the ``deadline_evicted`` taxonomy on top of the standard
        ``request_evicted`` audit trail."""
        from .. import telemetry
        now = time.perf_counter()
        expired = [r for r in self.sched.active_requests()
                   + list(self.sched.queue) if r.expired(now)]
        n = 0
        for req in expired:
            waited = now - req.submit_t
            if not self.evict(req, reason="deadline", requeue=False):
                continue
            n += 1
            telemetry.counter(
                "mxtpu_deadline_evictions_total",
                "live requests evicted on an expired deadline").inc()
            telemetry.record_event(
                "deadline_evicted", server=self.name, request=req.id,
                ttl_ms=req.ttl_ms, waited_s=round(waited, 4),
                generated=len(req.generated))
        return n

    def step(self, decode_steps: int = 1) -> dict:
        """One scheduling round: admit every queued request with a free
        slot (one prefill dispatch each), then advance every non-empty
        bucket by ``decode_steps`` tokens (ONE decode dispatch per
        bucket; ``decode_steps > 1`` uses the scan-bulked variant —
        one host sync per K tokens).  Returns round stats."""
        if self._poisoned is not None:
            raise MXNetError(
                "this Server's KV-cache pages were donated to a "
                "dispatch that failed and are no longer valid; call "
                "recover() to rebuild the pools and requeue resident "
                "requests (docs/serving.md). Original error: "
                f"{self._poisoned}")
        # deadline sweep FIRST: an expired queued request must not
        # consume the slot (and the prefill dispatch) it can no longer
        # use, and an expired resident frees its slot for this round's
        # admissions
        self._expire_deadlines()
        admitted = 0
        pending = self.sched.admissions()
        for i, (bucket, slot, req) in enumerate(pending):
            try:
                self._admit(bucket, slot, req)
            except Exception:
                # admissions() reserved EVERY slot up front: the
                # failed request and the ones behind it were placed
                # but never prefilled — release them back to the HEAD
                # of the queue (reverse order preserves FIFO), or a
                # retried step() would decode their zeroed pages as if
                # they held real prompts.  When the pool is POISONED,
                # recover() requeues every resident instead.
                if self._poisoned is None:
                    for _b, _s, r in reversed(pending[i:]):
                        self.sched.evict(r, reason="admit_aborted",
                                         requeue=True)
                raise
            admitted += 1
        tokens = 0
        for bucket in self.sched.buckets:
            if bucket.n_active() == 0:
                continue
            tokens += self._decode(bucket, int(decode_steps))
        self._update_gauges()
        return {"admitted": admitted, "tokens": tokens,
                "active": len(self.sched.active_requests()),
                "queued": self.sched.queue_depth()}

    def run(self, decode_steps: int = 1,
            max_rounds: Optional[int] = None) -> int:
        """Step until every submitted request finished; returns rounds
        run.  ``max_rounds`` bounds runaway loops (default: generous
        budget derived from the workload)."""
        if max_rounds is None:
            pending = len(self.sched.active_requests()) \
                + self.sched.queue_depth()
            max_rounds = 16 + pending * (self.max_new_tokens + 2)
        rounds = 0
        while (self.sched.active_requests()
               or self.sched.queue_depth()):
            if rounds >= max_rounds:
                raise MXNetError(
                    f"serving run() exceeded {max_rounds} rounds with "
                    "requests still live — scheduler wedged?")
            self.step(decode_steps=decode_steps)
            rounds += 1
        return rounds

    def generate(self, prompts, max_new_tokens: Optional[int] = None,
                 temperature: float = 0.0,
                 decode_steps: int = 1) -> List[np.ndarray]:
        """Batch convenience: submit every prompt, run to drain, and
        return ``prompt + continuation`` per request (in order)."""
        reqs = [self.submit(p, max_new_tokens=max_new_tokens,
                            temperature=temperature) for p in prompts]
        self.run(decode_steps=decode_steps)
        return [r.tokens() for r in reqs]

    def evict(self, req: Request, reason: str = "user",
              requeue: bool = False) -> bool:
        """Remove a live request (slot or queue); returns True when it
        was live (a request that already finished is left untouched —
        no event, no counter).  Retained ``request_evicted`` event +
        counter; ``requeue=True`` restarts it from its prompt (the
        recovery path)."""
        from .. import telemetry
        if not self.sched.evict(req, reason, requeue=requeue):
            return False
        telemetry.counter("mxtpu_serving_requests_evicted_total",
                          "requests evicted from the serving plane"
                          ).inc()
        telemetry.record_event("request_evicted", server=self.name,
                               request=req.id, reason=reason,
                               requeued=bool(requeue),
                               generated=len(req.generated))
        self._update_gauges()
        return True

    def recover(self) -> int:
        """Rebuild every poisoned (or healthy) KV-cache pool and
        requeue resident requests; clears the poison latch.  Returns
        the number of requests requeued.  The serving twin of the
        trainers' ``recover(manager)`` — state here is cache pages
        rebuilt by replaying host-owned prompts, so no checkpoint is
        involved."""
        from ..elastic.manager import record_recovery
        t0 = time.perf_counter()
        was_poisoned = self._poisoned is not None
        requeued = 0
        # reverse: evict(requeue=True) pushes to the queue HEAD, so
        # iterating backwards preserves the residents' relative order
        for req in reversed(self.sched.active_requests()):
            self.evict(req, reason="recover", requeue=True)
            requeued += 1
        for pool in self._pools.values():
            pool.reset()
        for b in self.sched.buckets:
            b.offsets[:] = 0.0
            b.active[:] = 0.0
            b.temps[:] = 0.0
            b.last_tokens[:] = 0.0
        self._poisoned = None
        record_recovery("serving", time.perf_counter() - t0,
                        was_poisoned, name=self.name,
                        requeued=requeued)
        return requeued

    # -- live resize (docs/elasticity.md, "Live resize" — serving leg) ----
    def _fresh_bucket_stats(self):
        return {b.key: {"steady_dispatches": 0, "tokens": 0,
                        "steady_misses": 0, "steady_fresh_compiles": 0}
                for b in self.sched.buckets}

    def resize_slots(self, new_slots: int,
                     reason: Optional[str] = None) -> dict:
        """Grow/shrink every bucket's slot count IN-JOB through the
        same prewarm -> drain -> migrate -> swap protocol the train
        plane's ``ResizeController`` runs (``elastic.resize``;
        typically driven by its ``ServingAutoscaler`` off the
        queue-depth/occupancy signals).

        * **prewarm** — every recorded bucket variant is AOT-compiled
          for the new slot count (``engine.aot_compile`` + the
          persistent tier) BEFORE anything moves, so the first
          post-swap dispatch is already steady state with 0 fresh
          compiles (the variants land pre-warmed in the steady
          accounting MXL601 audits).  Compile time is not downtime —
          the old buckets could still serve here.
        * **drain** — serving dispatches are synchronous, so between
          scheduling rounds nothing is in flight; this is the settled
          boundary (fault point ``resize_drain``) and where the
          downtime clock starts.
        * **migrate** — resident K/V pages gather into the new pool by
          slot index (one ``take`` per page tensor; generated tokens/
          offsets are host-owned and ride along), so live requests
          keep their progress.  On a shrink, residents beyond the new
          capacity are evicted-with-requeue (they replay from their
          host-owned prompts — the documented recovery semantics).
        * **swap** — buckets/pools/identities rebind; a failure after
          migration started crash-heals onto the NEW slot count with
          zeroed pages and every resident requeued (``recovery``
          telemetry), so the plane is never left unroutable.

        Returns the registry record (``elastic.resize.resizes``)."""
        from .. import engine
        from ..elastic import faults as _faults
        from ..elastic import resize as _resize
        from ..elastic.manager import record_recovery
        from .scheduler import Bucket
        import jax.numpy as jnp

        new_slots = int(new_slots)
        if new_slots < 1:
            raise MXNetError(f"resize_slots: need >= 1, got {new_slots}")
        if self._decode_sharding is not None:
            fan = self.plan.decode_fanout()
            if fan > 1 and new_slots % fan:
                raise MXNetError(
                    f"resize_slots: {new_slots} slot(s) do not divide "
                    f"the plan's decode fan-out {fan} "
                    f"({self.plan.decode}); pick a multiple")
        if self._poisoned is not None:
            raise MXNetError("server is poisoned; recover() before "
                             "resizing")
        old_counts = sorted({b.slots for b in self.sched.buckets})
        if old_counts == [new_slots]:
            raise MXNetError(
                f"resize_slots: already at {new_slots} slots")
        # a heterogeneous construction (per-bucket slot counts)
        # uniformizes on its first resize; the record keeps the real
        # before-state so slots_from never misreports a smaller bucket
        old_slots = old_counts[0] if len(old_counts) == 1 \
            else old_counts

        phase = "prewarm"
        try:
            # PREWARM: compile the new-slot programs while the old
            # buckets could still serve — a failure here leaves the
            # server untouched on the old configuration (same phase
            # order as the train controller: the downtime clock must
            # not start until the compiles are paid)
            _faults.maybe_fire("resize_prewarm")
            new_rows = [(new_slots, b.prompt_len, b.cache_len)
                        for b in self.sched.buckets]
            new_hash = self._compute_struct_hash(buckets=new_rows)
            new_base = f"serving_{self.lm.name}_{new_hash}"
            P = len(self._param_nds)
            shadow = {b.key: Bucket(new_slots, b.prompt_len,
                                    b.cache_len)
                      for b in self.sched.buckets}
            import jax
            prewarmed: Dict[str, dict] = {}
            for suffix, v in sorted(self._variants.items()):
                b = self._bucket_for_suffix(suffix)
                if b is None:
                    continue
                nb = shadow[b.key]
                kind, k = str(v["kind"]), int(v.get("k") or 0)
                L2 = 2 * self._pools[b.key].num_layers
                avals = list(engine.persist.sig_from_json(v["avals"]))
                for i, a in enumerate(avals):
                    # the slot dim is dim 0 of every cache page and —
                    # for decode — of the 4 per-slot extras (tok/off/
                    # active/temp); everything else (params, prefill
                    # extras, the RNG key) is slot-count-independent
                    per_slot = (P <= i < P + L2) or (
                        kind == "decode" and
                        P + L2 <= i < P + L2 + 4)
                    if per_slot and len(a) == 2 and a[0]:
                        avals[i] = ((new_slots,) + tuple(a[0][1:]),
                                    a[1])
                sds = [jax.ShapeDtypeStruct(a[0], np.dtype(a[1]))
                       for a in avals]
                new_suffix = self._suffix(nb, kind, k)
                pure = self._pure_for(nb, kind, k)
                engine.aot_compile(
                    self.name + new_suffix, pure, {}, sds,
                    donate=tuple(int(i) for i in v["donate"]),
                    persist_name=new_base + new_suffix)
                prewarmed[new_suffix] = {
                    "suffix": new_suffix, "kind": kind, "k": k,
                    "donate": [int(i) for i in v["donate"]],
                    "avals": engine.persist.sig_to_json(tuple(avals))}
            # DRAIN: the settled boundary (nothing in flight between
            # rounds); the downtime clock starts here — after the
            # pre-warm, whose compile time is NOT downtime
            phase = "drain"
            _faults.maybe_fire("resize_drain")
            t_drain = time.perf_counter()
        except Exception as e:
            # pre-migration failure: the server is untouched on the
            # old configuration — record the abort (the train
            # controller does the same for its pre-drain phases)
            _resize._note_failed("serving", phase, repr(e),
                                 name=self.name,
                                 still_on="old_config")
            raise

        healed = False
        heal_error = None
        migrated = 0
        requeued = 0
        try:
            # MIGRATE: resident pages gather into the new pools
            _faults.maybe_fire("resize_reshard")
            new_pools: Dict[tuple, KVCachePool] = {}
            new_buckets = []
            for b in list(self.sched.buckets):
                nb = shadow[b.key]
                residents = [(j, r) for j, r in enumerate(b.requests)
                             if r is not None]
                kept = residents[:new_slots]
                for _j, r in reversed(residents[new_slots:]):
                    self.evict(r, reason="resize_shrink", requeue=True)
                    requeued += 1
                npool = KVCachePool(self.lm, new_slots, b.cache_len,
                                    ctx=self.ctx,
                                    dtype=self.cache_dtype,
                                    sharding=self._decode_sharding)
                if kept:
                    idx = np.zeros((new_slots,), np.int32)
                    for j2, (j, _r) in enumerate(kept):
                        idx[j2] = j
                    flat = self._pools[b.key].flat()
                    if _faults._active:
                        # the donate-tuple discipline: every source
                        # page IS consumed by the move (deleted as the
                        # successors land), so the pre-filtered form
                        # is the whole pool
                        _faults.on_dispatch("serving_resize_migrate",
                                            flat, donate=None)
                    jidx = jnp.asarray(idx)
                    moved = [jnp.take(c, jidx, axis=0) for c in flat]
                    if self._decode_sharding is not None:
                        # adopt() bypasses _build_pages, so the plan's
                        # decode layout must be re-applied here or the
                        # migrated pages land wherever jnp.take put
                        # them (kvcache's "every page build" promise)
                        import jax as _jax
                        moved = [_jax.device_put(
                            m, self._decode_sharding) for m in moved]
                    # integrity audit (docs/elasticity.md, "Integrity
                    # sentry"): every migrated resident's K/V pages
                    # must checksum-match their source slot — a page
                    # corrupted in flight (or rotten in the source
                    # pool) raises HERE, which lands in the
                    # crash-heal below: the resident replays loudly
                    # from its host-owned prompt instead of decoding
                    # garbage on the new pool.  Gated like every
                    # other leg of the sentry (MXTPU_INTEGRITY=0
                    # skips it): the per-page host readbacks sit
                    # inside the measured migrate window
                    from ..elastic import integrity as _integrity
                    if _integrity.enabled():
                        for j2, (j, r) in enumerate(kept):
                            for ci, c in enumerate(flat):
                                if _integrity.page_checksum(c[j]) != \
                                        _integrity.page_checksum(
                                            moved[ci][j2]):
                                    raise MXNetError(
                                        f"KV-page checksum mismatch "
                                        f"migrating request {r.id} "
                                        f"slot {j}->{j2} (page "
                                        f"tensor {ci}): corrupt "
                                        "resident page; the request "
                                        "will be requeued and "
                                        "replayed")
                    npool.adopt(moved)
                    for c in flat:
                        try:
                            c.delete()
                        except Exception:
                            pass
                    migrated += len(kept)
                for j2, (j, _r) in enumerate(kept):
                    nb.adopt_slot(b, j, j2)
                new_pools[nb.key] = npool
                new_buckets.append(nb)
            # SWAP: rebind buckets/pools/identities
            _faults.maybe_fire("resize_swap")
            self.sched.buckets = sorted(new_buckets,
                                        key=lambda x: x.prompt_len)
            self._pools = new_pools
        except Exception as e:
            # crash-heal: cleanly on the NEW slot count with zeroed
            # pages and every resident requeued (prompts are
            # host-owned — the replay path recover() already proves)
            heal_error = repr(e)
            _resize._note_failed("serving", "reshard_swap", heal_error,
                                 name=self.name, heal="requeue_replay")
            t_heal = time.perf_counter()
            # `requeued` keeps the shrink-overflow evictions that
            # already landed in the queue before the fault — the
            # heal's sweep only finds the residents still in bucket
            # tables, and the record must count BOTH.
            # the OLD bucket tables still list every resident —
            # adopt_slot deliberately leaves the source row in place
            # until the swap commits, exactly so this sweep can find
            # requests mid-migration (their .bucket may already point
            # at a shadow bucket; evict releases through it)
            for b in list(self.sched.buckets):
                for r in reversed([r for r in b.requests
                                   if r is not None]):
                    # through Server.evict, not the bare scheduler:
                    # heal evictions must leave the same audit trail
                    # (retained request_evicted event + counter) as
                    # every other eviction — the failure path is where
                    # the flight recorder matters most
                    if self.evict(r, reason="resize_heal",
                                  requeue=True):
                        requeued += 1
            self.sched.buckets = sorted(
                (Bucket(new_slots, b.prompt_len, b.cache_len)
                 for b in shadow.values()),
                key=lambda x: x.prompt_len)
            self._pools = {
                b.key: KVCachePool(self.lm, new_slots, b.cache_len,
                                   ctx=self.ctx,
                                   dtype=self.cache_dtype,
                                   sharding=self._decode_sharding)
                for b in self.sched.buckets}
            self._poisoned = None
            migrated = 0
            healed = True
            record_recovery("resize_heal",
                            time.perf_counter() - t_heal, False,
                            name=self.name, requeued=requeued)
        self._bucket_stats = self._fresh_bucket_stats()
        # rows for buckets that no longer exist would make a later
        # save_signature manifest un-warm-startable; the current
        # configuration's prewarmed rows replace them, and the
        # variants are warm NOW — their first live dispatch is
        # already steady state (same rule as warm_start)
        self._variants = dict(prewarmed)
        self._warmed.update(prewarmed)
        self._struct_hash = new_hash
        self._persist_base = new_base
        self._persist_pinned = False
        rec = {
            "kind": "serving", "name": self.name,
            "slots_from": old_slots, "slots_to": new_slots,
            "buckets": [f"{b.slots}x{b.prompt_len}"
                        for b in self.sched.buckets],
            "prewarmed_variants": len(prewarmed),
            "migrated": migrated, "requeued": requeued,
            "healed": healed,
            "downtime_seconds": round(
                time.perf_counter() - t_drain, 4),
        }
        if reason:
            rec["autoscale_reason"] = reason
        if heal_error:
            rec["heal_error"] = heal_error[:300]
        _resize._note_completed(rec)
        self._update_gauges()
        return dict(rec)

    def stats(self) -> dict:
        """Live occupancy/queue stats plus per-bucket steady-state
        compile accounting (what ``analyze_serving`` reads): every
        dispatch of an already-warmed variant is bracketed with
        ``engine.compile_counts()``, so a nonzero
        ``steady_misses``/``steady_fresh_compiles`` means THIS bucket's
        programs kept compiling after they existed — the retrace
        signature continuous batching exists to prevent."""
        out = {"name": self.name, "occupancy": self.sched.occupancy(),
               "queue_depth": self.sched.queue_depth(),
               "poisoned": self._poisoned is not None,
               "warm_started": self.warm_started, "buckets": {}}
        for b in self.sched.buckets:
            out["buckets"][f"{b.slots}x{b.prompt_len}"] = \
                dict(self._bucket_stats[b.key])
        return out

    # -- AOT warm start (docs/compile_cache.md, serving leg) --------------
    def save_signature(self, path: str) -> str:
        """Write the serving warm-start manifest: every dispatched
        bucket variant's avals + donation layout + the persistent-tier
        identity.  A fresh process (same model/bucket construction)
        feeds it to :meth:`warm_start` to precompile the whole plane
        before the first request."""
        from .. import engine
        if not self._variants:
            raise MXNetError(
                "save_signature: serve at least one request first "
                "(no compiled variants recorded)")
        manifest = {
            "format": 1, "kind": "mxtpu_serving_plane",
            "fingerprint": engine.persist.fingerprint(),
            # the canonical plan pin (docs/parallelism.md): None for
            # plan-less servers, so pre-planner manifests still serve
            "plan": self.plan.to_record() if self.plan is not None
            else None,
            "net": self.lm.name,
            "persist_base": self._persist_base,
            "struct_hash": self._struct_hash,
            "max_new_tokens": self.max_new_tokens,
            "top_k": self._kk, "cache_dtype": self.cache_dtype,
            "buckets": [
                {"slots": b.slots, "prompt_len": b.prompt_len,
                 "cache_len": b.cache_len}
                for b in self.sched.buckets],
            "variants": [self._variants[k]
                         for k in sorted(self._variants)],
        }
        tmp = path + f".tmp{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(manifest, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.remove(tmp)  # a failed write must not leak .tmp*
            except OSError:
                pass
            raise
        return path

    def warm_start(self, path: str) -> bool:
        """Precompile every variant a :meth:`save_signature` manifest
        records — persistent-tier reload when the cache dir holds the
        executables, fresh AOT compile otherwise — so the first
        request is served with 0 fresh compiles.  Never raises for a
        bad/mismatched manifest: returns False (with a ``warm_start``
        telemetry event carrying the reason) and the plane compiles on
        first use as it always did."""
        from .. import engine, telemetry

        def _fail(reason):
            telemetry.record_event("warm_start", name=self.name,
                                   ok=False, reason=reason)
            return False

        try:
            with open(path) as f:
                m = json.load(f)
        except (OSError, ValueError) as e:
            return _fail(f"unreadable manifest: {e!r}"[:300])
        if m.get("kind") != "mxtpu_serving_plane" or \
                m.get("format") != 1:
            return _fail("not an mxtpu_serving_plane manifest")
        if m.get("fingerprint") != engine.persist.fingerprint():
            return _fail("environment fingerprint mismatch "
                         "(jax/jaxlib/platform/salt)")
        # the plan pin is compared FIRST and by field, so a rejection
        # names the exact diverging rule/field instead of the opaque
        # struct hash (fail-open: cold compile, never a crash)
        from ..parallel import planner as _planner
        plan_diff = _planner.diff_records(
            m.get("plan"),
            self.plan.to_record() if self.plan is not None else None)
        if plan_diff is not None:
            return _fail(f"sharding-plan mismatch: {plan_diff}")
        if m.get("struct_hash") != self._struct_hash:
            return _fail("structural hash mismatch: the manifest "
                         "describes a different model/bucket/sampler "
                         "configuration")
        want = sorted((b["slots"], b["prompt_len"], b["cache_len"])
                      for b in m.get("buckets", ()))
        have = sorted((b.slots, b.prompt_len, b.cache_len)
                      for b in self.sched.buckets)
        if want != have:
            return _fail(f"bucket mismatch: manifest {want} vs "
                         f"configured {have}")
        if self._poisoned is not None:
            return _fail("server is poisoned")
        try:
            import jax
            self._persist_base = m["persist_base"]
            self._persist_pinned = True
            sources = {}
            for v in m.get("variants", ()):
                suffix = str(v["suffix"])
                bucket = self._bucket_for_suffix(suffix)
                if bucket is None:
                    return _fail(f"variant {suffix!r} names no "
                                 "configured bucket")
                pure = self._pure_for(bucket, str(v["kind"]),
                                      int(v.get("k") or 0))
                sds = [jax.ShapeDtypeStruct(a[0], np.dtype(a[1]))
                       for a in engine.persist.sig_from_json(v["avals"])]
                name = self.name + suffix
                sources[name] = engine.aot_compile(
                    name, pure, {}, sds,
                    donate=tuple(int(i) for i in v["donate"]),
                    persist_name=self._persist_base + suffix)
                self._variants[suffix] = v
                # the variant is warm NOW: its first live dispatch is
                # already steady state, so a fresh compile there (a
                # corrupt/evicted persist entry, aval drift from the
                # manifest) lands in the steady accounting instead of
                # hiding as "first dispatch pays its compile"
                self._warmed.add(suffix)
            if not sources:
                return _fail("manifest has no compiled variants")
        except Exception as e:
            return _fail(f"warm-start failed: {e!r}"[:300])
        self.warm_started = True
        telemetry.record_event("warm_start", name=self.name, ok=True,
                               sources=sources)
        return True

    # -- program builders --------------------------------------------------
    def _suffix(self, bucket, kind: str, k: int = 0) -> str:
        return f"_b{bucket.slots}x{bucket.prompt_len}_{kind}" + \
            (f"{k}" if k else "")

    def _bucket_for_suffix(self, suffix: str):
        for b in self.sched.buckets:
            if suffix.startswith(f"_b{b.slots}x{b.prompt_len}_"):
                return b
        return None

    def _pure_for(self, bucket, kind: str, k: int = 0):
        key = self._suffix(bucket, kind, k)
        fn = self._pure_cache.get(key)
        if fn is None:
            if kind == "prefill":
                fn = self._make_prefill(bucket)
            elif kind == "decode" and not k:
                fn = self._make_decode(bucket)
            elif kind == "decode" and k:
                fn = self._make_decode_multi(bucket, k)
            else:
                raise MXNetError(f"unknown serving variant {kind!r}")
            self._pure_cache[key] = fn
        return fn

    def _pick(self, logits, temp, active, keys, vmapped=True):
        """Greedy + temperature/top-k sampler (traced): per-row pick of
        ``argmax`` (temp == 0) or categorical over the truncated,
        temperature-scaled logits."""
        import jax
        import jax.numpy as jnp
        from jax import lax
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.float32)
        lg = logits.astype(jnp.float32) / \
            jnp.maximum(temp[:, None], 1e-6)
        if self._kk:
            kth = lax.top_k(lg, self._kk)[0][:, -1:]
            lg = jnp.where(lg < kth, -jnp.inf, lg)
        sampled = jax.vmap(jax.random.categorical)(keys, lg) \
            .astype(jnp.float32)
        nxt = jnp.where(temp > 0, sampled, greedy)
        return jnp.where(active > 0, nxt, jnp.zeros_like(nxt))

    def _make_decode(self, bucket):
        lm, ctx = self.lm, self.ctx
        params = self._param_nds
        P, L = len(params), len(lm.model.layers)
        N = bucket.slots

        def decode_pure(*flat):
            import jax
            import jax.numpy as jnp
            from ..gluon import block as block_mod
            from ..ndarray.ndarray import NDArray
            param_vals = list(flat[:P])
            cache_vals = flat[P:P + 2 * L]
            tok, off, active, temp, key_raw = flat[P + 2 * L:]
            with block_mod.tracing_scope(params, param_vals):
                shells = [(NDArray(cache_vals[2 * i], ctx=ctx),
                           NDArray(cache_vals[2 * i + 1], ctx=ctx))
                          for i in range(L)]
                logits = lm.decode_step(
                    NDArray(tok, ctx=ctx), shells,
                    NDArray(off, ctx=ctx))._data
                new_caches = tuple(s._data for pair in shells
                                   for s in pair)
            k0 = jax.random.wrap_key_data(key_raw)
            keys = jax.vmap(lambda i: jax.random.fold_in(k0, i))(
                jnp.arange(N))
            nxt = self._pick(logits, temp, active, keys)
            return (nxt,) + new_caches

        return decode_pure

    def _make_decode_multi(self, bucket, k_steps: int):
        lm, ctx = self.lm, self.ctx
        params = self._param_nds
        P, L = len(params), len(lm.model.layers)
        N = bucket.slots

        def decode_multi_pure(*flat):
            import jax
            import jax.numpy as jnp
            from jax import lax
            from ..gluon import block as block_mod
            from ..ndarray.ndarray import NDArray
            param_vals = list(flat[:P])
            cache_vals = tuple(flat[P:P + 2 * L])
            tok, off, active, temp, key_raw = flat[P + 2 * L:]
            k0 = jax.random.wrap_key_data(key_raw)

            def body(carry, step_i):
                tok_c, off_c, caches = carry
                with block_mod.tracing_scope(params, param_vals):
                    shells = [(NDArray(caches[2 * i], ctx=ctx),
                               NDArray(caches[2 * i + 1], ctx=ctx))
                              for i in range(L)]
                    logits = lm.decode_step(
                        NDArray(tok_c, ctx=ctx), shells,
                        NDArray(off_c, ctx=ctx))._data
                    new_caches = tuple(s._data for pair in shells
                                       for s in pair)
                k_step = jax.random.fold_in(k0, step_i)
                keys = jax.vmap(
                    lambda i: jax.random.fold_in(k_step, i))(
                    jnp.arange(N))
                nxt = self._pick(logits, temp, active, keys)
                # inactive slots hold position (offset AND token), so
                # the in-graph carry matches the host's bookkeeping
                return (nxt.reshape(N, 1), off_c + active,
                        new_caches), nxt

            (_, _, caches_f), toks = lax.scan(
                body, (tok, off, cache_vals),
                jnp.arange(k_steps))
            return (toks,) + caches_f          # toks: (K, N)

        return decode_multi_pure

    def _make_prefill(self, bucket):
        lm, ctx = self.lm, self.ctx
        params = self._param_nds
        P, L = len(params), len(lm.model.layers)
        S = bucket.prompt_len
        cdt = self.cache_dtype

        def prefill_pure(*flat):
            import jax
            import jax.numpy as jnp
            from jax import lax
            from ..gluon import block as block_mod
            from ..ndarray.ndarray import NDArray
            param_vals = list(flat[:P])
            cache_vals = flat[P:P + 2 * L]
            prompt, last_pos, slot, temp, key_raw = flat[P + 2 * L:]
            dt = jnp.dtype(cdt)
            with block_mod.tracing_scope(params, param_vals):
                tmp = []
                for layer in lm.model.layers:
                    a = layer.attn
                    shp = (1, S, a._kv, a._d)
                    tmp.append((NDArray(jnp.zeros(shp, dt), ctx=ctx),
                                NDArray(jnp.zeros(shp, dt), ctx=ctx)))
                logits = lm.prefill(
                    NDArray(prompt, ctx=ctx), tmp,
                    last_pos=NDArray(last_pos, ctx=ctx))._data
                tmp_flat = [s._data for pair in tmp for s in pair]
            slot_i = jnp.asarray(slot, jnp.int32)
            zero = jnp.int32(0)
            new_caches = []
            for i in range(2 * L):
                c = cache_vals[i]
                new_caches.append(lax.dynamic_update_slice(
                    c, tmp_flat[i].astype(c.dtype),
                    (slot_i, zero, zero, zero)))
            k0 = jax.random.wrap_key_data(key_raw)
            keys = jax.vmap(lambda i: jax.random.fold_in(k0, i))(
                slot_i.reshape(1))
            nxt = self._pick(logits, temp, jnp.ones((1,)), keys)
            return (nxt,) + tuple(new_caches)

        return prefill_pure

    # -- dispatch ----------------------------------------------------------
    def _dispatch(self, bucket, kind: str, extra, k: int = 0):
        """One engine dispatch of a bucket program with the pool
        donated; returns the non-cache outputs with the successor pool
        adopted.  Post-donation failures poison the bucket (the
        recovery half lives in :meth:`recover`)."""
        from .. import engine, telemetry
        pool = self._pools[bucket.key]
        if pool.poisoned is not None:
            raise MXNetError(
                f"bucket {bucket.key} pool is poisoned "
                f"({pool.poisoned}); call recover()")
        suffix = self._suffix(bucket, kind, k)
        pure = self._pure_for(bucket, kind, k)
        P = len(self._param_nds)
        L2 = 2 * pool.num_layers
        if self._decode_sharding is not None:
            # the planned decode mesh: params ride as the replicated
            # copies placed at construction, and every per-dispatch
            # extra (tokens/offsets/temps/key) is committed replicated
            # — one coherent SPMD program, no mixed-device inputs
            import jax as _jax
            extra = [_jax.device_put(e, self._repl_sharding)
                     for e in extra]
            params_flat = list(self._placed_params)
        else:
            params_flat = [p._data for p in self._param_nds]
        flat = params_flat + pool.flat() + list(extra)
        donate = tuple(range(P, P + L2))
        name = self.name + suffix
        persist_name = self._persist_base + suffix
        m0, f0 = engine.compile_counts()
        # the step-owner bracket doubles as the guardian plane's
        # heartbeat: a hung serving dispatch is watchdog-visible
        # exactly like a hung train step, and the bracket encloses the
        # poison latch so a Guardian(action='recover') sees the
        # poisoned server at the heartbeat's exit (elastic.guardian)
        with telemetry.step_owner(self, "serving_dispatch"):
            try:
                res = engine.invoke_compiled(name, pure, {}, *flat,
                                             donate=donate,
                                             persist_name=persist_name)
            except Exception as e:
                if pool.consumed():
                    pool.poison(repr(e))
                    self._poisoned = repr(e)
                    telemetry.counter(
                        "mxtpu_poisons_total",
                        "post-donation failures (training state lost)"
                        ).inc()
                    telemetry.record_event(
                        "poison", where="serving", name=name,
                        error=repr(e)[:500])
                    telemetry.auto_dump(
                        reason=f"serving_poisoned:{name}")
                    raise MXNetError(
                        "serving dispatch failed AFTER the KV-cache "
                        "pool was donated; call Server.recover() to "
                        "rebuild the pages and requeue resident "
                        "requests (docs/serving.md). Original error: "
                        f"{e!r}") from e
                raise
        n_out = len(res) - L2
        pool.adopt(res[n_out:])
        if suffix not in self._variants:
            self._variants[suffix] = {
                "suffix": suffix, "kind": kind, "k": k,
                "donate": [int(i) for i in donate],
                "avals": engine.persist.sig_to_json(
                    engine.persist.aval_sig(flat))}
            # the wire auditor (analysis.wire_passes): serving decode/
            # prefill legs classify via the plan's decode spec; no
            # observatory reconciliation (program="") — serving wire
            # is GSPMD-implicit on the decode mesh
            try:
                from ..analysis import wire_passes as _wire
                _wire.note_step(
                    f"serving:{self.lm.name}", suffix, pure, flat,
                    plan=self.plan, kind=kind, program="")
            except Exception:
                pass
        if suffix not in self._warmed:
            # first dispatch of this variant pays its compile; every
            # later one is steady state and must compile NOTHING
            self._warmed.add(suffix)
        else:
            m1, f1 = engine.compile_counts()
            stats = self._bucket_stats[bucket.key]
            stats["steady_dispatches"] += 1
            stats["steady_misses"] += m1 - m0
            stats["steady_fresh_compiles"] += f1 - f0
        return res[:n_out]

    def _admit(self, bucket, slot: int, req: Request):
        from .. import random as _rnd
        from .. import telemetry
        t0 = time.perf_counter()
        S = bucket.prompt_len
        prompt = np.zeros((1, S), np.float32)
        prompt[0, :req.prompt_len] = req.prompt
        extra = [prompt,
                 np.asarray([req.prompt_len - 1], np.float32),
                 np.asarray(slot, np.float32),
                 np.asarray([req.temperature], np.float32),
                 _rnd._next_key_nd(self.ctx)._data]
        # pre-dispatch failures (trace/compile, retries exhausted)
        # propagate to step(), which releases THIS placement and the
        # ones behind it back to the queue in FIFO order
        out = self._dispatch(bucket, "prefill", extra)
        tok = int(np.asarray(out[0])[0])     # host sync: TTFT is real
        telemetry.counter("mxtpu_serving_prefills_total",
                          "admission prefill dispatches").inc()
        bucket.last_tokens[slot] = float(tok)
        self._bucket_stats[bucket.key]["tokens"] += 1
        telemetry.counter("mxtpu_serving_tokens_total",
                          "tokens generated by the serving plane").inc()
        finished = req.push_token(tok)
        telemetry.histogram(
            "mxtpu_serving_ttft_seconds",
            "submit -> first generated token (s)").observe(
            req.first_token_t - req.submit_t)
        telemetry.histogram(
            "mxtpu_serving_prefill_seconds",
            "one admission (prefill dispatch + first token) (s)"
            ).observe(time.perf_counter() - t0)
        if finished:
            self._finish(req)

    def _decode(self, bucket, decode_steps: int) -> int:
        from .. import random as _rnd
        from .. import telemetry
        t0 = time.perf_counter()
        k = max(1, int(decode_steps))
        active_snap = bucket.active.copy()
        extra = [bucket.last_tokens.reshape(bucket.slots, 1).copy(),
                 bucket.offsets.copy(), active_snap.copy(),
                 bucket.temps.copy(),
                 _rnd._next_key_nd(self.ctx)._data]
        out = self._dispatch(bucket, "decode", extra,
                             k=0 if k == 1 else k)
        toks = np.asarray(out[0])
        if toks.ndim == 1:
            toks = toks[None, :]               # (K, N)
        # host bookkeeping mirrors the in-graph carry: offsets advance
        # K per slot ACTIVE AT DISPATCH (release() rewinds finishers)
        bucket.offsets += k * active_snap
        produced = 0
        for row in toks:
            for j in np.nonzero(active_snap > 0)[0]:
                req = bucket.requests[int(j)]
                if req is None or req.state != ACTIVE:
                    continue               # finished mid-K: overrun rows
                tok = int(row[int(j)])
                bucket.last_tokens[int(j)] = float(tok)
                produced += 1
                if req.push_token(tok):
                    self._finish(req)
        dt = time.perf_counter() - t0
        telemetry.histogram("mxtpu_serving_decode_seconds",
                            "one decode dispatch wall clock (s)"
                            ).observe(dt)
        if produced:
            telemetry.counter(
                "mxtpu_serving_tokens_total",
                "tokens generated by the serving plane").inc(produced)
        self._bucket_stats[bucket.key]["tokens"] += produced
        return produced

    def _finish(self, req: Request):
        from .. import telemetry
        self.sched.finish(req)
        telemetry.counter("mxtpu_serving_requests_completed_total",
                          "requests run to completion").inc()
        if req.done_t is not None:
            telemetry.histogram(
                "mxtpu_serving_request_seconds",
                "submit -> completion per-request latency (s)"
                ).observe(req.done_t - req.submit_t)

    def _update_gauges(self):
        from .. import telemetry
        telemetry.gauge("mxtpu_serving_batch_occupancy",
                        "active slots / total slots").set(
            self.sched.occupancy())
        telemetry.gauge("mxtpu_serving_queue_depth",
                        "requests waiting for a slot").set(
            self.sched.queue_depth())
