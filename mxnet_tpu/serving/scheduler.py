"""Continuous-batching scheduler: admit/evict between decode steps
into FIXED bucket shapes (docs/serving.md).

The TPU contract that shapes this module: a compiled program exists
per ``(batch_slots, prompt_len_bucket)`` pair and NOTHING else may
vary.  So the scheduler never changes shapes — admission swaps a
slot's cache page + flips its active-mask bit, eviction flips the bit
back, and the decode program runs the same avals every step.  Steady
state therefore performs ZERO retraces across any admit/evict
sequence (asserted in tier-1 via ``engine.cache_info()``).

Pure host logic: no jax, no dispatches.  ``Server`` (``server.py``)
owns the compiled programs and drives this scheduler between them.
"""
from __future__ import annotations

import itertools
import time
from collections import deque
from typing import List, Optional

import numpy as np

from ..base import MXNetError

__all__ = ["Request", "Bucket", "BucketScheduler"]

_req_uid = itertools.count(1)

#: request lifecycle states (``shed`` = rejected at admission by the
#: overload policy — never held a slot or a queue place)
QUEUED, ACTIVE, DONE, EVICTED, SHED = \
    "queued", "active", "done", "evicted", "shed"


class Request:
    """One generation request moving through the serving plane.

    ``ttl_ms`` arms the overload policy (docs/serving.md, "Overload
    policy"): the request must COMPLETE within ``ttl_ms`` of
    submission or it is shed at enqueue (the estimated queue wait
    already exceeds the deadline) / evicted when the deadline expires
    in the queue or in a slot.  ``None`` (default) = no deadline."""

    __slots__ = ("id", "prompt", "max_new_tokens", "temperature",
                 "eos_id", "state", "generated", "bucket", "slot",
                 "submit_t", "first_token_t", "done_t", "evict_reason",
                 "ttl_ms", "deadline")

    def __init__(self, prompt, max_new_tokens: int,
                 temperature: float = 0.0,
                 eos_id: Optional[int] = None,
                 ttl_ms: Optional[float] = None):
        self.id = next(_req_uid)
        self.prompt = np.asarray(prompt, dtype=np.float32).reshape(-1)
        if self.prompt.size == 0:
            raise MXNetError("empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise MXNetError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        self.temperature = float(temperature)
        self.eos_id = None if eos_id is None else int(eos_id)
        self.state = QUEUED
        self.generated: List[int] = []
        self.bucket: Optional["Bucket"] = None
        self.slot: Optional[int] = None
        self.submit_t = time.perf_counter()
        self.first_token_t: Optional[float] = None
        self.done_t: Optional[float] = None
        self.evict_reason: Optional[str] = None
        if ttl_ms is not None and float(ttl_ms) <= 0:
            raise MXNetError(f"ttl_ms must be > 0, got {ttl_ms}")
        self.ttl_ms = None if ttl_ms is None else float(ttl_ms)
        self.deadline = None if ttl_ms is None else \
            self.submit_t + self.ttl_ms / 1000.0

    def expired(self, now: Optional[float] = None) -> bool:
        """Deadline passed while the request is still live (queued OR
        holding a slot)?  Terminal states never expire."""
        if self.deadline is None or self.state in (DONE, EVICTED, SHED):
            return False
        return (time.perf_counter() if now is None else now) \
            > self.deadline

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.size)

    def tokens(self) -> np.ndarray:
        """Prompt + generated continuation (what the caller reads
        back)."""
        return np.concatenate(
            [self.prompt, np.asarray(self.generated, np.float32)])

    def push_token(self, tok: int) -> bool:
        """Record one generated token; returns True when the request
        just FINISHED (hit eos or its token budget)."""
        if self.first_token_t is None:
            self.first_token_t = time.perf_counter()
        self.generated.append(int(tok))
        if self.eos_id is not None and int(tok) == self.eos_id:
            return True
        return len(self.generated) >= self.max_new_tokens


class Bucket:
    """One fixed ``(slots, prompt_len)`` shape class and its host-side
    slot table.  ``cache_len = prompt_len + max_new_tokens`` positions
    per slot; per-slot decode offsets are the ABSOLUTE next position
    (they drive rope + the cache scatter + the validity mask as
    dynamic inputs)."""

    def __init__(self, slots: int, prompt_len: int, cache_len: int):
        if slots < 1 or prompt_len < 1 or cache_len <= prompt_len:
            raise MXNetError(
                f"bad bucket (slots={slots}, prompt_len={prompt_len}, "
                f"cache_len={cache_len}): need slots/prompt_len >= 1 "
                "and cache_len > prompt_len")
        self.slots = int(slots)
        self.prompt_len = int(prompt_len)
        self.cache_len = int(cache_len)
        self.requests: List[Optional[Request]] = [None] * self.slots
        self.offsets = np.zeros(self.slots, np.float32)
        self.active = np.zeros(self.slots, np.float32)
        self.temps = np.zeros(self.slots, np.float32)
        self.last_tokens = np.zeros(self.slots, np.float32)

    @property
    def key(self):
        return (self.slots, self.prompt_len)

    def n_active(self) -> int:
        return int(self.active.sum())

    def occupancy(self) -> float:
        return self.n_active() / self.slots

    def free_slot(self) -> Optional[int]:
        for j, r in enumerate(self.requests):
            if r is None:
                return j
        return None

    def place(self, req: Request, slot: int):
        """Host bookkeeping of an admission (the cache page itself is
        written by the admit program)."""
        if self.requests[slot] is not None:
            raise MXNetError(f"slot {slot} is occupied")
        self.requests[slot] = req
        req.state = ACTIVE
        req.bucket, req.slot = self, slot
        # the admit program samples the first token at prompt_len-1's
        # logits; decode continues at absolute position prompt_len
        self.offsets[slot] = float(req.prompt_len)
        self.active[slot] = 1.0
        self.temps[slot] = req.temperature

    def adopt_slot(self, src: "Bucket", j: int, j2: int):
        """Move ``src``'s slot ``j`` bookkeeping into THIS bucket's
        slot ``j2`` — the host half of a live slot-count resize
        (``Server.resize_slots``): the request keeps its absolute
        offset / temperature / last token (its K/V page migrates by
        the same index on the device side), only its (bucket, slot)
        address changes."""
        req = src.requests[j]
        if req is None:
            raise MXNetError(f"adopt_slot: source slot {j} is empty")
        if self.requests[j2] is not None:
            raise MXNetError(f"adopt_slot: slot {j2} is occupied")
        self.requests[j2] = req
        req.bucket, req.slot = self, j2
        self.offsets[j2] = src.offsets[j]
        self.active[j2] = 1.0
        self.temps[j2] = src.temps[j]
        self.last_tokens[j2] = src.last_tokens[j]

    def release(self, slot: int):
        """Drop a slot back to free: active-mask off, offset rewound.
        The page contents stay as garbage the per-row validity mask
        never exposes to other slots."""
        req = self.requests[slot]
        self.requests[slot] = None
        self.active[slot] = 0.0
        self.offsets[slot] = 0.0
        self.temps[slot] = 0.0
        self.last_tokens[slot] = 0.0
        if req is not None:
            req.bucket, req.slot = None, None


class BucketScheduler:
    """FIFO admission over fixed buckets + a bounded wait queue.

    ``buckets``: list of ``(slots, prompt_len)`` pairs (one compiled
    prefill and decode program each).  A request lands in the SMALLEST
    bucket whose ``prompt_len`` holds its prompt (right-padded there);
    prompts longer than every bucket are rejected.  The queue is
    bounded by ``max_queue`` — overflow is the ``slot_oom`` signal
    (the caller records the retained telemetry event).
    """

    def __init__(self, buckets, max_new_tokens: int, max_queue: int):
        if not buckets:
            raise MXNetError("need at least one (slots, prompt_len) "
                             "bucket")
        self.max_new_tokens = int(max_new_tokens)
        self.max_queue = int(max_queue)
        self.buckets: List[Bucket] = [
            Bucket(s, p, p + self.max_new_tokens)
            for s, p in sorted(buckets, key=lambda b: b[1])]
        if len({b.prompt_len for b in self.buckets}) != len(self.buckets):
            raise MXNetError("duplicate prompt_len buckets")
        # no terminal-request registry: callers hold their own Request
        # references, and a server-side dict of every finished request
        # would grow without bound on a production stream
        self.queue: deque = deque()

    # -- admission --------------------------------------------------------
    def select_bucket(self, prompt_len: int) -> Optional[Bucket]:
        for b in self.buckets:
            if prompt_len <= b.prompt_len:
                return b
        return None

    def enqueue(self, req: Request) -> Bucket:
        """Queue ``req`` for admission; raises ``MXNetError`` when no
        bucket fits the prompt or the queue is full (callers emit the
        ``slot_oom`` event for the latter)."""
        bucket = self.select_bucket(req.prompt_len)
        if bucket is None:
            raise MXNetError(
                f"prompt of {req.prompt_len} tokens exceeds the "
                f"largest bucket "
                f"({self.buckets[-1].prompt_len}); add a bigger "
                "prompt-length bucket")
        if len(self.queue) >= self.max_queue:
            raise MXNetError(
                f"serving queue full ({self.max_queue}); evict or "
                "raise MXTPU_SERVING_MAX_QUEUE")
        self.queue.append(req)
        return bucket

    def admissions(self):
        """Pop every queued request whose bucket has a free slot:
        returns ``[(bucket, slot, request)]`` in FIFO order (a request
        whose bucket is full never blocks one whose bucket has room).
        Each returned request is already PLACED (slot reserved, mask
        on) so later queue entries cannot race it; the caller
        dispatches the admit program per entry — and must release a
        placement whose dispatch failed (``Server.step`` requeues the
        ones behind a failure)."""
        out = []
        blocked = deque()
        while self.queue:
            req = self.queue.popleft()
            bucket = self.select_bucket(req.prompt_len)
            slot = bucket.free_slot()
            if slot is None:
                blocked.append(req)
                continue
            # reserve so a later queued request cannot take the slot
            bucket.place(req, slot)
            out.append((bucket, slot, req))
        self.queue = blocked
        return out

    # -- completion / eviction --------------------------------------------
    def finish(self, req: Request):
        req.state = DONE
        req.done_t = time.perf_counter()
        if req.bucket is not None and req.slot is not None:
            req.bucket.release(req.slot)

    def evict(self, req: Request, reason: str,
              requeue: bool = False) -> bool:
        """Remove a live request from its slot (or the queue); returns
        True when anything happened.  A request already in a terminal
        state (DONE/EVICTED) is left untouched — evicting a request
        that finished in the same scheduling round must not wipe its
        output or skew the lifecycle counters.  With ``requeue=True``
        the request restarts from its prompt at the next admission
        round (the recovery path)."""
        if req.state in (DONE, EVICTED):
            return False
        if req.bucket is not None and req.slot is not None:
            req.bucket.release(req.slot)
        elif req in self.queue:
            self.queue.remove(req)
        req.evict_reason = reason
        if requeue:
            req.state = QUEUED
            req.generated = []
            req.first_token_t = None
            # head, not tail: a requeued request (transient admit
            # failure, recovery) keeps its place ahead of
            # later-submitted traffic — callers requeueing a batch
            # iterate it in REVERSE to preserve relative order
            self.queue.appendleft(req)
        else:
            req.state = EVICTED
        return True

    def active_requests(self) -> List[Request]:
        return [r for b in self.buckets for r in b.requests
                if r is not None]

    def queue_depth(self) -> int:
        return len(self.queue)

    def occupancy(self) -> float:
        total = sum(b.slots for b in self.buckets)
        used = sum(b.n_active() for b in self.buckets)
        return used / total if total else 0.0
