"""``mx.rtc``: user-authored device kernels.

Capability parity: reference ``python/mxnet/rtc.py`` — ``CudaModule``
runtime-compiles user CUDA source via NVRTC and launches kernels on
NDArrays (SURVEY.md §2.2 "Fused pointwise codegen ... user-facing RTC
via mx.rtc.CudaModule").

TPU-native design: the kernel language is **Pallas** (the TPU kernel
DSL that plays NVRTC/CUDA-C's role here), so a "module" holds Python
kernel *functions* operating on ``Ref``s instead of CUDA source
strings.  ``get_kernel(...).launch(args, ctx, ...)`` keeps the
reference's launch surface: grid in units of blocks, one output spec
per output, compile-once caching per (kernel, shapes, grid).  On a
non-TPU backend kernels run under the Pallas interpreter, so user
kernels are testable on the CPU suite exactly like the in-tree flash
attention kernel.

    import mxnet_tpu as mx
    from mxnet_tpu import nd, rtc

    def axpy(x_ref, y_ref, o_ref, *, alpha):
        o_ref[...] = alpha * x_ref[...] + y_ref[...]

    mod = rtc.PallasModule({"axpy": axpy})
    k = mod.get_kernel("axpy", alpha=2.0)
    (out,) = k.launch([x, y], out_shapes=[x.shape])
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

from .base import MXNetError

__all__ = ["PallasModule", "CudaModule"]


def _interpret_default() -> bool:
    from .base import on_accelerator
    return not on_accelerator()


def _specs_key(specs) -> Tuple:
    """Structural cache key for BlockSpec lists: block shape + the
    index_map's compiled code/closure.  Rebuilding an *equal* spec per
    launch (the idiomatic pattern) therefore hits the cache instead of
    recompiling the kernel each step."""
    if specs is None:
        return ()
    out = []
    for s in specs:
        bs = getattr(s, "block_shape", None)
        im = getattr(s, "index_map", None)
        # pallas wraps the user function in _IndexMapFunc; unwrap to
        # reach the code object
        im = getattr(im, "index_map", im)
        code = getattr(im, "__code__", None)
        if code is not None:
            closure = getattr(im, "__closure__", None) or ()
            imk = (code.co_code, repr(code.co_consts),
                   tuple(repr(c.cell_contents) for c in closure))
        else:
            imk = repr(im)
        out.append((tuple(bs) if bs is not None else None, imk))
    return tuple(out)


_RTC_SEQ = functools.partial(next, __import__("itertools").count())


class PallasKernel:
    """A launchable kernel (parity: ``CudaKernel``); compile-once per
    (shapes, dtypes, out spec, grid, BlockSpecs) via ``jax.jit`` over
    ``pallas_call``."""

    def __init__(self, name: str, fn: Callable, static_kwargs: dict,
                 interpret: Optional[bool]):
        self._name = name
        self._fn = fn
        self._static = dict(static_kwargs)
        self._interpret = interpret
        # key (incl. structural BlockSpec keys) -> OpDef; structural
        # keying means idiomatic callers that rebuild equal specs each
        # launch still hit the cache instead of recompiling per step
        self._compiled: Dict[Tuple, Any] = {}

    def _build(self, out_shapes, out_dtypes, grid, in_specs, out_specs,
               scratch_shapes):
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        body = (functools.partial(self._fn, **self._static)
                if self._static else self._fn)
        interpret = (self._interpret if self._interpret is not None
                     else _interpret_default())
        out_shape = [jax.ShapeDtypeStruct(s, jnp.dtype(d))
                     for s, d in zip(out_shapes, out_dtypes)]
        kwargs: Dict[str, Any] = {"interpret": interpret}
        if grid is not None:
            kwargs["grid"] = grid
        if in_specs is not None:
            kwargs["in_specs"] = in_specs
        if out_specs is not None:
            kwargs["out_specs"] = (out_specs if len(out_shapes) > 1
                                   else out_specs[0])
        if scratch_shapes:
            kwargs["scratch_shapes"] = scratch_shapes
        call = pl.pallas_call(
            body,
            out_shape=out_shape if len(out_shape) > 1 else out_shape[0],
            **kwargs)
        return jax.jit(call)

    def launch(self, args: Sequence, ctx=None, grid=None,
               out_shapes: Sequence[Tuple[int, ...]] = (),
               out_dtypes: Sequence = (), in_specs=None, out_specs=None,
               scratch_shapes=()):
        """Run the kernel on NDArray/array args; returns NDArray tuple.

        ``grid`` plays the reference launch config's grid role (block
        shape lives in the BlockSpecs); ``out_shapes`` sizes each
        output (the reference mutated pre-allocated args instead).
        """
        from .ndarray.ndarray import NDArray, invoke
        from .ops.registry import OpDef

        if not out_shapes:
            raise MXNetError("PallasKernel.launch: out_shapes required")
        nds = [a if isinstance(a, NDArray) else NDArray(a) for a in args]
        if ctx is not None:  # reference launch semantics: ctx places it
            nds = [a.as_in_context(ctx) for a in nds]
        arrs = [a._data for a in nds]
        if not out_dtypes:
            out_dtypes = [arrs[0].dtype if arrs else "float32"] \
                * len(out_shapes)
        grid = tuple(grid) if isinstance(grid, (list, tuple)) else grid
        key = (tuple(a.shape for a in arrs),
               tuple(str(a.dtype) for a in arrs),
               tuple(tuple(s) for s in out_shapes),
               tuple(str(d) for d in out_dtypes), grid,
               _specs_key(in_specs), _specs_key(out_specs),
               repr(scratch_shapes))
        op = self._compiled.get(key)
        if op is None:
            fn = self._build([tuple(s) for s in out_shapes],
                             list(out_dtypes), grid, in_specs, out_specs,
                             scratch_shapes)
            fn._mxtpu_no_jit = True  # already jitted above
            # monotonic op names: never collide even across gc'd kernels
            op = OpDef(f"_rtc_{self._name}_{_RTC_SEQ()}", fn, len(arrs),
                       len(out_shapes), (), False, None)
            self._compiled[key] = op
        out = invoke(op, nds, ctx=ctx)
        return out if isinstance(out, (list, tuple)) else (out,)


class PallasModule:
    """A named collection of Pallas kernels (parity: ``CudaModule``)."""

    def __init__(self, kernels: Dict[str, Callable]):
        if not isinstance(kernels, dict) or not kernels:
            raise MXNetError(
                "PallasModule takes {name: kernel_fn}; kernel source "
                "strings are a CUDA/NVRTC concept — on TPU, kernels are "
                "Pallas functions")
        self._kernels = dict(kernels)

    def get_kernel(self, name: str, interpret: Optional[bool] = None,
                   **static_kwargs) -> PallasKernel:
        """Bind static kwargs now; shapes/grid resolve at launch."""
        try:
            fn = self._kernels[name]
        except KeyError:
            raise MXNetError(
                f"kernel {name!r} not in module "
                f"(have {sorted(self._kernels)})") from None
        return PallasKernel(name, fn, static_kwargs, interpret)


class CudaModule:
    """Reference-name shim: CUDA source cannot run on TPU."""

    def __init__(self, *args, **kwargs):
        raise MXNetError(
            "mx.rtc.CudaModule compiles CUDA source via NVRTC and has "
            "no TPU equivalent; author the kernel as a Pallas function "
            "and use mx.rtc.PallasModule (same launch surface)")
