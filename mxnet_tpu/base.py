"""Base utilities: errors, type helpers, env-flag registry access.

Capability parity: reference ``python/mxnet/base.py`` (ctypes plumbing,
``MXNetError``, ``check_call``).  There is no C ABI boundary on the hot path
here — dispatch goes straight to PJRT through JAX — so this module only keeps
the user-visible pieces: the exception type and small shared helpers.
"""
from __future__ import annotations

import numpy as np

__all__ = ["MXNetError", "numeric_types", "string_types", "integer_types",
           "on_accelerator"]


class MXNetError(RuntimeError):
    """Error raised by the framework (parity with mxnet.base.MXNetError)."""


def on_accelerator() -> bool:
    """True when jax's default backend is the TPU chip.

    Experimental PJRT plugins register their platform under their OWN
    name — the axon tunnel has shown up as ``"axon"`` in some sessions
    and ``"tpu"`` in others — so TPU gates must never string-match
    ``== "tpu"`` (that silently turned the flash kernels off for a
    whole session).  A denylist of platforms KNOWN not to be a TPU
    keeps unknown plugin spellings on the TPU path without enabling
    Mosaic kernels on e.g. a CUDA backend.
    """
    import jax
    try:
        return jax.default_backend() not in (
            "cpu", "gpu", "cuda", "rocm", "metal")
    except Exception:
        return False


numeric_types = (float, int, np.generic)
integer_types = (int, np.integer)
string_types = (str,)


def _as_list(obj):
    """Return obj as a list: lists/tuples pass through, scalars wrap."""
    if isinstance(obj, (list, tuple)):
        return list(obj)
    return [obj]
