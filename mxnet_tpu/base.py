"""Base utilities: errors, type helpers, env-flag registry access.

Capability parity: reference ``python/mxnet/base.py`` (ctypes plumbing,
``MXNetError``, ``check_call``).  There is no C ABI boundary on the hot path
here — dispatch goes straight to PJRT through JAX — so this module only keeps
the user-visible pieces: the exception type and small shared helpers.
"""
from __future__ import annotations

import numpy as np

__all__ = ["MXNetError", "numeric_types", "string_types", "integer_types"]


class MXNetError(RuntimeError):
    """Error raised by the framework (parity with mxnet.base.MXNetError)."""


numeric_types = (float, int, np.generic)
integer_types = (int, np.integer)
string_types = (str,)


def _as_list(obj):
    """Return obj as a list: lists/tuples pass through, scalars wrap."""
    if isinstance(obj, (list, tuple)):
        return list(obj)
    return [obj]
