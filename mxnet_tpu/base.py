"""Base utilities: errors, type helpers, env-flag registry access.

Capability parity: reference ``python/mxnet/base.py`` (ctypes plumbing,
``MXNetError``, ``check_call``).  There is no C ABI boundary on the hot path
here — dispatch goes straight to PJRT through JAX — so this module only keeps
the user-visible pieces: the exception type and small shared helpers.
"""
from __future__ import annotations

import numpy as np

__all__ = ["MXNetError", "numeric_types", "string_types", "integer_types",
           "on_accelerator"]


class MXNetError(RuntimeError):
    """Error raised by the framework (parity with mxnet.base.MXNetError)."""


# platforms already warned about by on_accelerator (warn once per name)
_WARNED_PLATFORMS: set = set()


def on_accelerator() -> bool:
    """True when jax's default backend is the TPU chip.

    Experimental PJRT plugins register their platform under their OWN
    name — the axon tunnel has shown up as ``"axon"`` in some sessions
    and ``"tpu"`` in others — so TPU gates must never string-match
    ``== "tpu"`` (that silently turned the flash kernels off for a
    whole session).  A denylist of platforms KNOWN not to be a TPU
    keeps unknown plugin spellings on the TPU path without enabling
    Mosaic kernels on e.g. a CUDA backend.
    """
    import jax
    try:
        plat = jax.default_backend()
    except Exception:
        return False
    if plat in ("cpu", "gpu", "cuda", "rocm", "metal"):
        return False
    if plat not in ("tpu", "axon") and plat not in _WARNED_PLATFORMS:
        # denylist consequence (ADVICE r4): an unknown NON-TPU plugin
        # ('neuron', 'xpu', ...) is treated as TPU here and will
        # hard-fail in Mosaic/Pallas lowering — warn once so the
        # resulting error is attributable
        _WARNED_PLATFORMS.add(plat)
        import warnings
        warnings.warn(
            f"on_accelerator: unrecognized PJRT platform {plat!r} "
            f"treated as TPU; Mosaic/Pallas kernels will be enabled "
            f"and will fail if this is not a TPU "
            f"(set MXTPU_DISABLE_FLASH=1 to keep XLA paths)",
            stacklevel=2)
    return True


numeric_types = (float, int, np.generic)
integer_types = (int, np.integer)
string_types = (str,)


def _as_list(obj):
    """Return obj as a list: lists/tuples pass through, scalars wrap."""
    if isinstance(obj, (list, tuple)):
        return list(obj)
    return [obj]
