"""Memory & communication observatory: the DEVICE side of the plane.

PR 4's telemetry watches the host (dispatches, retraces, stalls); this
module watches HBM and the interconnect.  The engine's tiered AOT seam
does an explicit ``lower().compile()``, so a compiled-executable object
exists for every cached program — and XLA already computed everything
worth knowing about it:

* ``compiled.memory_analysis()`` — argument / output / temp /
  generated-code bytes per device, from which a peak-footprint figure
  follows (``arg + out + temp + code - aliased``);
* ``compiled.cost_analysis()`` — FLOPs and bytes-accessed;
* the compiled HLO text — every collective op (all-reduce /
  reduce-scatter / all-gather / all-to-all / collective-permute) with
  its per-device payload shape, from which analytic bytes-on-wire
  follow (ring formulas over the replica-group size);
* the donate tuple — bytes the step does NOT double-buffer, summed
  from the donated arguments' avals.

Everything here is NEVER-RAISES and gated on the telemetry master
switch: ``MXTPU_TELEMETRY=0`` harvests nothing, records nothing, and
costs one attribute load per seam.  ``cost_analysis``/
``memory_analysis`` are backend-dependent; when they raise or return
nothing (CPU, older jaxlib) the harvest degrades to analytic aval-based
estimates and a single ``mem_analysis_unavailable`` event is recorded
for the whole process, not one per program.

The live side: :func:`census` walks the engine's live-buffer set for
per-device HBM bytes; :func:`param_census` attributes bytes to gluon
parameters by name; ``oom_risk`` events fire when live + peak
approaches the device capacity (``device.memory_stats()`` — absent on
CPU, so the check is inert there).

Consumers: ``engine.cache_info()["memory"]``, ``tools/mxmem.py``,
``bench.py``'s per-stage ``memory`` block, and the mxlint rules
MXL308/MXL309 (``analysis.analyze_memory``).  See
docs/observability.md ("Device memory & comms").
"""
from __future__ import annotations

import re
import threading
from typing import Any, Dict, List, Optional, Tuple

from . import _switch
from .metrics import gauge
from .recorder import record_event

__all__ = [
    "harvest_compiled", "programs", "collective_stats", "census",
    "param_census", "note_param_tree", "param_trees",
    "opt_state_census", "note_opt_state", "opt_state_trees", "report",
    "dump_report", "device_capacity", "reset",
    "OOM_RISK_RATIO",
]

_lock = threading.Lock()
#: program name -> harvest record (latest aval signature wins; the
#: record counts how many signatures/harvests it has absorbed)
_programs: Dict[str, dict] = {}
#: registered param trees (SPMD trainers): name -> layout snapshot,
#: the MXL309 input
_param_trees: Dict[str, dict] = {}
#: registered optimizer-state layouts (SPMD trainers): name -> census,
#: the MXL310 input and the ZeRO memory-drop evidence
_opt_trees: Dict[str, dict] = {}
# the unavailable event is per PROCESS, not per program — a CPU run
# compiles hundreds of programs and one event says it all
_unavailable_reported = [False]
# monotonically stamps each harvest so report() can pick "the variant
# that actually ran last" when a program has step_multi bulk variants
_harvest_seq = [0]
_capacity_cache: List[Any] = []      # [] = unprobed, [None] = unknown

#: live + peak above this fraction of device capacity emits ``oom_risk``
OOM_RISK_RATIO = 0.92

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1, "f8e4m3fnuz": 1,
    "f8e5m2fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

# one HLO collective definition: ``%name = <shape-or-tuple> all-reduce(``.
# Async pairs count via their ``-done`` half, whose result type is
# exactly the collective's result; ``-start`` definitions are SKIPPED —
# their tuple type interleaves operands with results (e.g.
# ``(f32[8,128], f32[64,128]) all-gather-start``), so summing it would
# overcount payloads by ~the operand size.  Tuple types allow one level
# of nesting (variadic starts/dones).
_COLL_RE = re.compile(
    r"=\s*(?P<ty>\((?:[^()]|\([^()]*\))*\)"
    r"|[a-z0-9\-]+\[[^\]]*\](?:\{[^}]*\})?)\s+"
    r"%?(?P<op>all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"collective-permute|collective-broadcast)(?P<suffix>-start|-done)?\(")
_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]")
_GROUPS_LIST_RE = re.compile(r"replica_groups=\{\{([0-9, ]*)\}")
_DEF_RE = re.compile(r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=")
_BULK_SUFFIX_RE = re.compile(r"_k\d+r?$")
_OPERAND_RE = re.compile(r"%([\w.\-]+)")


# -- aval arithmetic ---------------------------------------------------------

def _aval_entry_bytes(entry) -> int:
    """Bytes of one ``persist.aval_sig`` entry; 1-tuples (non-array
    leaves — python scalars riding as weak-typed inputs) count 0."""
    if len(entry) != 2:
        return 0
    import numpy as np
    shape, dtype = entry
    n = 1
    for d in shape:
        n *= int(d)
    try:
        return n * np.dtype(dtype).itemsize
    except TypeError:
        return 0


def _flatten_args(args, donate) -> Tuple[list, set]:
    """Per-positional-arg flattening: ``(flat aval list, donated flat
    index set)``.  ``donate`` holds POSITIONAL argnums (what
    ``jax.jit(donate_argnums=...)`` takes); pytree args (the SPMD
    trainer passes tuples) flatten to several leaves each, so the flat
    index set is derived per arg, not assumed 1:1."""
    from ..engine import persist
    donate_set = set(int(d) for d in donate)
    flat: list = []
    donated: set = set()
    for i, a in enumerate(args):
        leaves = persist.aval_sig([a])
        start = len(flat)
        flat.extend(leaves)
        if i in donate_set:
            donated.update(range(start, start + len(leaves)))
    return flat, donated


# -- HLO collective walk -----------------------------------------------------

def _shape_bytes(type_str: str) -> int:
    total = 0
    for dtype, dims in _SHAPE_RE.findall(type_str):
        size = _DTYPE_BYTES.get(dtype)
        if size is None:
            continue            # token types (s32[] indices still match)
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * size
    return total


def _wire_bytes(op: str, payload: int, k: int) -> int:
    """Analytic per-device bytes-on-wire for one collective (ring
    algorithm; ``payload`` = the op's per-device RESULT bytes, ``k`` =
    replica-group size).  all-reduce moves 2N(k-1)/k (reduce-scatter +
    all-gather phases); reduce-scatter's HLO result is the scattered
    1/k shard, so its N(k-1)/k reads ``result*(k-1)``; all-gather's
    result is the full gathered tensor, N(k-1)/k directly."""
    if k <= 1:
        return 0
    if op == "all-reduce":
        return int(2 * payload * (k - 1) / k)
    if op == "reduce-scatter":
        return int(payload * (k - 1))
    if op in ("all-gather", "all-to-all"):
        return int(payload * (k - 1) / k)
    # collective-permute / collective-broadcast: the payload crosses
    # the wire once
    return int(payload)


def _group_size(line: str) -> Optional[int]:
    g = _GROUPS_IOTA_RE.search(line)
    if g:
        return int(g.group(2))
    g = _GROUPS_LIST_RE.search(line)
    if g:
        return len([t for t in g.group(1).split(",") if t.strip()])
    return None


def collective_stats(hlo_text: str,
                     default_group: Optional[int] = None) -> dict:
    """Count collective ops in compiled HLO text and derive analytic
    traffic: ``{kind: {count, payload_bytes, wire_bytes}}`` plus a
    ``total_wire_bytes`` roll-up.  Payloads are the per-device result
    bytes XLA printed (async pairs counted once, at the ``-done``);
    group size comes from ``replica_groups`` on the instruction — or
    its paired ``-start``, where the attribute lives for async forms —
    falling back to ``default_group`` or the process device count."""
    kinds: Dict[str, dict] = {}
    total_wire = 0
    start_groups: Dict[str, int] = {}
    for line in hlo_text.splitlines():
        m = _COLL_RE.search(line)
        if m is None:
            continue
        if m.group("suffix") == "-start":
            # payload counted at the paired -done, whose result type
            # is the collective's actual result (see _COLL_RE note);
            # remember the group size the -done line won't carry
            k = _group_size(line)
            d = _DEF_RE.match(line)
            if k and d:
                start_groups[d.group(1)] = k
            continue
        op = m.group("op")
        payload = _shape_bytes(m.group("ty"))
        k = _group_size(line)
        if not k and m.group("suffix") == "-done":
            for opname in _OPERAND_RE.findall(line[m.end():]):
                if opname in start_groups:
                    k = start_groups[opname]
                    break
        if not k:
            k = default_group
        if not k:
            try:
                import jax
                k = jax.device_count()
            except Exception:
                k = 1
        row = kinds.setdefault(
            op, {"count": 0, "payload_bytes": 0, "wire_bytes": 0})
        row["count"] += 1
        row["payload_bytes"] += payload
        wire = _wire_bytes(op, payload, k)
        row["wire_bytes"] += wire
        total_wire += wire
    return {"kinds": kinds, "total_wire_bytes": total_wire}


# -- harvest -----------------------------------------------------------------

def _note_unavailable(name: str, what: str, err: str):
    with _lock:
        if _unavailable_reported[0]:
            return
        _unavailable_reported[0] = True
    record_event("mem_analysis_unavailable", op=name, what=what,
                 error=err[:200])


def _memory_stats(name, compiled) -> Optional[dict]:
    try:
        stats = compiled.memory_analysis()
        if stats is None:
            raise ValueError("memory_analysis returned None")
        return {
            "argument_bytes": int(stats.argument_size_in_bytes),
            "output_bytes": int(stats.output_size_in_bytes),
            "temp_bytes": int(stats.temp_size_in_bytes),
            "generated_code_bytes":
                int(stats.generated_code_size_in_bytes),
            "alias_bytes": int(stats.alias_size_in_bytes),
        }
    except Exception as e:
        _note_unavailable(name, "memory_analysis", repr(e))
        return None


def _cost_stats(name, compiled) -> Optional[dict]:
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else None
        if not isinstance(cost, dict):
            raise ValueError(f"cost_analysis returned {type(cost)}")
        out = {}
        if "flops" in cost:
            out["flops"] = float(cost["flops"])
        if "bytes accessed" in cost:
            out["bytes_accessed"] = float(cost["bytes accessed"])
        return out or None
    except Exception as e:
        _note_unavailable(name, "cost_analysis", repr(e))
        return None


def device_capacity() -> Optional[int]:
    """Per-device memory capacity in bytes (``bytes_limit`` from
    ``device.memory_stats()``), or None where the backend does not
    report one (CPU) — the oom-risk check is inert then.  Probed once
    per process."""
    if not _capacity_cache:
        cap = None
        try:
            import jax
            stats = jax.local_devices()[0].memory_stats()
            if stats:
                cap = int(stats.get("bytes_limit") or 0) or None
        except Exception:
            cap = None
        _capacity_cache.append(cap)
    return _capacity_cache[0]


def _check_oom_risk(name: str, peak_bytes: Optional[int],
                    argument_bytes: Optional[int]):
    cap = device_capacity()
    if not cap or not peak_bytes:
        return
    from .. import engine
    live = engine.live_bytes()
    # the program's arguments (params, states, inputs) are themselves
    # live buffers, so live + peak would double-count them; the
    # program's NEW demand on top of what already resides is
    # peak - arguments (output + temp + code)
    extra = max(0, peak_bytes - (argument_bytes or 0))
    if live + extra > OOM_RISK_RATIO * cap:
        record_event(
            "oom_risk", op=name, live_bytes=live,
            program_peak_bytes=peak_bytes,
            program_extra_bytes=extra, capacity_bytes=cap,
            ratio=round((live + extra) / cap, 4))


def _single_device() -> bool:
    """True when the process sees one device — no program can carry a
    cross-device collective, so the HLO-text walk is pure waste."""
    try:
        import jax
        return jax.device_count() <= 1
    except Exception:
        return False


def harvest_compiled(name: str, compiled, args=(), donate=(),
                     out_avals=None, source: str = "fresh",
                     kind: str = "program",
                     cached_memory: Optional[dict] = None
                     ) -> Optional[dict]:
    """Record everything XLA knows about one compiled program.

    Called from the engine's tiered AOT seam (fresh compiles AND
    persistent-tier reloads) — never raises, returns the record (or
    ``None`` with telemetry disabled).  ``args`` are the call's
    positional arguments (arrays / ShapeDtypeStructs / pytrees of
    them); ``donate`` the positional donate argnums; ``out_avals`` the
    flattened output avals when the caller has them (``lowered
    .out_info`` — absent on deserialized executables, which only
    narrows MXL308, nothing else).  ``cached_memory`` is a persist
    entry's saved compact block: its per-kind collective table is
    reused so a warm-start reload never re-renders HLO text (which can
    be tens of MB for a large fused step) on the path the persistent
    cache exists to make fast.
    """
    if not _switch.enabled:
        return None
    try:
        from ..engine import persist
        in_avals, donated = _flatten_args(args, donate)
        donation_saved = sum(_aval_entry_bytes(in_avals[j])
                             for j in sorted(donated))
        mem = _memory_stats(name, compiled)
        analytic = mem is None
        if analytic:
            # aval-based estimate: argument bytes are exact, outputs/
            # temp unknowable without the executable's word
            mem = {"argument_bytes": sum(_aval_entry_bytes(e)
                                         for e in in_avals),
                   "output_bytes": None, "temp_bytes": None,
                   "generated_code_bytes": None, "alias_bytes": None}
            peak = mem["argument_bytes"]
        else:
            peak = (mem["argument_bytes"] + mem["output_bytes"]
                    + mem["temp_bytes"] + mem["generated_code_bytes"]
                    - mem["alias_bytes"])
        cost = _cost_stats(name, compiled)
        coll = None
        if cached_memory is not None and \
                isinstance(cached_memory.get("collectives"), dict):
            coll = {"kinds": cached_memory["collectives"],
                    "total_wire_bytes":
                        cached_memory.get("collective_wire_bytes") or 0}
        elif _single_device():
            # a one-device program cannot contain cross-device
            # collectives; skip rendering its HLO text entirely
            coll = {"kinds": {}, "total_wire_bytes": 0}
        else:
            try:
                coll = collective_stats(compiled.as_text())
            except Exception as e:
                _note_unavailable(name, "as_text", repr(e))
        out_sig = None
        if out_avals is not None:
            try:
                out_sig = persist.aval_sig(list(out_avals))
            except Exception:
                out_sig = None
        rec = {
            "name": name, "kind": kind, "source": source,
            "analytic": analytic, "peak_bytes": peak,
            **mem,
            "donation_saved_bytes": int(donation_saved),
            "donated_args": len(donated),
            "flops": (cost or {}).get("flops"),
            "bytes_accessed": (cost or {}).get("bytes_accessed"),
            "collectives": (coll or {}).get("kinds", {}),
            "collective_wire_bytes":
                (coll or {}).get("total_wire_bytes", 0),
            "in_avals": in_avals, "donated_idx": sorted(donated),
            "out_avals": out_sig,
        }
        with _lock:
            prev = _programs.get(name)
            rec["harvests"] = (prev["harvests"] + 1) if prev else 1
            _harvest_seq[0] += 1
            rec["seq"] = _harvest_seq[0]
            _programs[name] = rec
            max_peak = max((r["peak_bytes"] or 0)
                           for r in _programs.values())
        gauge("mxtpu_program_peak_bytes",
              "largest per-device peak footprint (arg+out+temp+code-"
              "alias) among harvested programs").set(max_peak)
        if donated:
            gauge("mxtpu_donation_saved_bytes",
                  "HBM bytes the most recently harvested donating "
                  "program avoids double-buffering").set(donation_saved)
        if rec["collective_wire_bytes"]:
            gauge("mxtpu_collective_bytes_per_step",
                  "analytic per-device bytes-on-wire of the most "
                  "recently harvested collective-bearing program"
                  ).set(rec["collective_wire_bytes"])
        _check_oom_risk(name, peak, mem["argument_bytes"])
        return rec
    except Exception:
        # the observatory must never cost a dispatch or a compile
        return None


def programs() -> Dict[str, dict]:
    """Snapshot of every harvested program record (name -> record)."""
    with _lock:
        return {k: dict(v) for k, v in _programs.items()}


# -- live-buffer + param census ----------------------------------------------

def _sharding_info(v) -> Tuple[str, bool]:
    """``(spec string, fully-replicated?)`` of one device array — THE
    replicated-detection rule MXL309 (params) and MXL310 (optimizer
    state) both judge by, so the two censuses can never disagree on
    what "replicated" means."""
    spec = ""
    replicated = True
    try:
        s = v.sharding
        spec = str(getattr(s, "spec", ""))
        replicated = not any(
            ax is not None for ax in getattr(s, "spec", ()) or ())
    except Exception:
        pass
    return spec, replicated

def census() -> dict:
    """Per-device HBM bytes of the engine's live tracked buffers:
    ``{"total_bytes", "count", "by_device"}``.  Donated/deleted buffers
    are skipped (the ``waitall`` guard); per-device attribution comes
    from addressable shards, so a replicated array counts once per
    device holding it.  Updates the ``mxtpu_hbm_live_bytes`` gauge."""
    from .. import engine
    total = 0
    count = 0
    by_device: Dict[str, int] = {}
    for arr in engine.live_arrays():
        try:
            if getattr(arr, "is_deleted", lambda: False)():
                continue
            nb = int(arr.nbytes)
        except Exception:
            continue
        total += nb
        count += 1
        try:
            for shard in arr.addressable_shards:
                dev = str(shard.device)
                by_device[dev] = by_device.get(dev, 0) \
                    + int(shard.data.nbytes)
        except Exception:
            by_device["unknown"] = by_device.get("unknown", 0) + nb
    if _switch.enabled:
        gauge("mxtpu_hbm_live_bytes",
              "bytes of live (non-donated, non-deleted) tracked "
              "device buffers").set(total)
    return {"total_bytes": total, "count": count,
            "by_device": by_device}


def _param_items(params):
    if hasattr(params, "collect_params"):
        params = params.collect_params()
    if hasattr(params, "items"):
        return list(params.items())
    out = []
    for p in params:
        out.append((getattr(p, "name", repr(p)), p))
    return out


def param_census(params) -> dict:
    """Attribute HBM bytes to gluon parameters by name.

    ``params`` may be a block (``collect_params()`` is called), a
    ``ParameterDict``, or an iterable of Parameters.  Rows are sorted
    largest first; ``total_bytes`` is their sum (deferred-init
    parameters carry no buffer yet and are skipped).  Each row records
    the sharding spec and whether the buffer is fully replicated —
    the MXL309 signal."""
    rows = []
    total = 0
    for name, p in _param_items(params):
        try:
            d = p.data()
            v = d._data
            nb = int(v.nbytes)
        except Exception:
            continue
        spec, replicated = _sharding_info(v)
        rows.append({"name": name, "shape": list(d.shape),
                     "dtype": str(d.dtype), "nbytes": nb,
                     "sharding": spec, "replicated": replicated})
        total += nb
    rows.sort(key=lambda r: -r["nbytes"])
    return {"params": rows, "total_bytes": total, "count": len(rows)}


def note_param_tree(name: str, params, mesh=None,
                    dp_axis: Optional[str] = None):
    """Register a sharded param layout for the MXL309 pass (called by
    ``DataParallelTrainer`` after placing its params on the mesh).  A
    snapshot, not a live view — re-registering under the same name
    replaces it.  No-op with telemetry disabled."""
    if not _switch.enabled:
        return
    try:
        tree = param_census(params)
        mesh_size = 1
        dp_size = 1
        if mesh is not None:
            try:
                for v in mesh.shape.values():
                    mesh_size *= int(v)
                if dp_axis is not None:
                    dp_size = int(mesh.shape.get(dp_axis, 1))
            except Exception:
                pass
        tree["mesh_size"] = mesh_size
        tree["dp_size"] = dp_size
        tree["dp_axis"] = dp_axis
        with _lock:
            _param_trees[name] = tree
    except Exception:
        pass


def param_trees() -> Dict[str, dict]:
    with _lock:
        return {k: dict(v) for k, v in _param_trees.items()}


def opt_state_census(leaves) -> dict:
    """Attribute HBM bytes to optimizer-state leaves, split into
    per-replica SHARDED vs REPLICATED residency.

    ``leaves``: iterable of ``(label, jax array)`` (what
    ``DataParallelTrainer._opt_state_leaves`` registers).  Each row
    records global bytes, per-DEVICE bytes (the sharding's
    ``shard_shape`` — a leaf sharded over dp counts 1/dp per device),
    and the replicated flag.  ``per_device_bytes = replicated_bytes +
    sharded_bytes_per_device`` is the figure the ZeRO ~dp x drop is
    measured against (gauge ``mxtpu_optimizer_state_bytes``)."""
    import numpy as np
    rows = []
    total = 0
    per_device = 0
    sharded_pd = 0
    repl_b = 0
    for name, v in leaves:
        try:
            nb = int(v.nbytes)
        except Exception:
            continue
        spec, replicated = _sharding_info(v)
        pd = nb
        try:
            shard_shape = v.sharding.shard_shape(v.shape)
            pd = int(np.prod(shard_shape)) * int(v.dtype.itemsize)
        except Exception:
            pass
        rows.append({"name": str(name), "shape": list(v.shape),
                     "dtype": str(v.dtype), "nbytes": nb,
                     "bytes_per_device": pd, "sharding": spec,
                     "replicated": replicated})
        total += nb
        per_device += pd
        if replicated:
            repl_b += nb
        else:
            sharded_pd += pd
    rows.sort(key=lambda r: -r["nbytes"])
    return {"leaves": rows, "count": len(rows), "total_bytes": total,
            "per_device_bytes": per_device,
            "replicated_bytes": repl_b,
            "sharded_bytes_per_device": sharded_pd}


def note_opt_state(name: str, leaves, mesh=None,
                   dp_axis: Optional[str] = None, zero_stage: int = 0):
    """Register a trainer's optimizer-state layout (called by
    ``DataParallelTrainer`` after state creation).  A snapshot —
    re-registering under the same name replaces it.  Sets the
    ``mxtpu_optimizer_state_bytes`` gauge to the per-device total so
    the ZeRO drop is measurable, not asserted.  No-op with telemetry
    disabled."""
    if not _switch.enabled:
        return
    try:
        tree = opt_state_census(leaves)
        mesh_size = 1
        dp_size = 1
        if mesh is not None:
            try:
                for v in mesh.shape.values():
                    mesh_size *= int(v)
                if dp_axis is not None:
                    dp_size = int(mesh.shape.get(dp_axis, 1))
            except Exception:
                pass
        tree["mesh_size"] = mesh_size
        tree["dp_size"] = dp_size
        tree["dp_axis"] = dp_axis
        tree["zero_stage"] = int(zero_stage)
        with _lock:
            _opt_trees[name] = tree
        gauge("mxtpu_optimizer_state_bytes",
              "per-device optimizer-state bytes of the most recently "
              "registered trainer (replicated + sharded shard)"
              ).set(tree["per_device_bytes"])
    except Exception:
        pass


def opt_state_trees() -> Dict[str, dict]:
    with _lock:
        return {k: dict(v) for k, v in _opt_trees.items()}


# -- reporting ---------------------------------------------------------------

def _compact(rec: dict) -> dict:
    """A program record without its aval lists (the report/cache_info
    face; the full record stays in :func:`programs`)."""
    return {k: v for k, v in rec.items()
            if k not in ("in_avals", "out_avals", "donated_idx")}


def _latest_per_base(recs) -> List[dict]:
    """One record per LOGICAL program: ``step_multi`` bulking harvests
    ``<base>_k{K}[r]`` variants of the same train step (the scan-body
    collective still reads as one inner step's traffic), so summing a
    base with its bulk variants would double-count per-step numbers.
    Keeps the most recently harvested variant of each base."""
    latest: Dict[str, dict] = {}
    for r in recs:
        base = _BULK_SUFFIX_RE.sub("", r.get("name") or "")
        prev = latest.get(base)
        if prev is None or (r.get("seq") or 0) > (prev.get("seq") or 0):
            latest[base] = r
    return list(latest.values())


def report(top_n: Optional[int] = None, params=None) -> dict:
    """The observatory's one-call summary: top-N programs by peak
    bytes, the live-buffer census, collective traffic, device capacity,
    and (when ``params`` is given) the per-param HBM table.  This is
    what ``tools/mxmem.py`` renders and ``bench.py`` embeds."""
    if top_n is None:
        from .. import envs
        top_n = envs.get("MXTPU_MEM_REPORT_TOP_N")
    progs = sorted(programs().values(),
                   key=lambda r: -(r["peak_bytes"] or 0))
    coll: Dict[str, dict] = {}
    for r in _latest_per_base(progs):
        for op, row in (r.get("collectives") or {}).items():
            agg = coll.setdefault(
                op, {"count": 0, "payload_bytes": 0, "wire_bytes": 0})
            for k in agg:
                agg[k] += row.get(k, 0)
    out = {
        "n_programs": len(progs),
        "programs": [_compact(r) for r in progs[:max(0, int(top_n))]],
        "live": census(),
        "collectives": coll,
        "device_capacity_bytes": device_capacity(),
    }
    if params is not None:
        out["param_census"] = param_census(params)
    opt_trees = opt_state_trees()
    if opt_trees:
        out["opt_states"] = opt_trees
    return out


def dump_report(path: str, top_n: Optional[int] = None,
                params=None) -> str:
    """Write :func:`report` as a JSON artifact ``tools/mxmem.py
    render`` can display offline; returns the path."""
    import json
    import os
    rep = report(top_n=top_n, params=params)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(rep, f, indent=1, default=str)
    os.replace(tmp, path)
    return path


def cache_info_block() -> dict:
    """The ``engine.cache_info()["memory"]`` view: per-program compact
    records plus roll-ups.  Empty when nothing harvested (telemetry
    off, or no tiered compiles yet)."""
    with _lock:
        progs = {k: _compact(v) for k, v in _programs.items()}
    if not progs:
        return {"programs": 0, "per_program": {}}
    per_base = _latest_per_base(progs.values())
    return {
        "programs": len(progs),
        "max_peak_bytes": max((r["peak_bytes"] or 0)
                              for r in progs.values()),
        "donation_saved_bytes": sum(r["donation_saved_bytes"]
                                    for r in per_base),
        "collective_wire_bytes": sum(r["collective_wire_bytes"]
                                     for r in per_base),
        "per_program": progs,
    }


def reset():
    """Forget every harvested program, param tree, and the
    once-per-process unavailable flag (test isolation; part of
    ``telemetry.reset()``).  The device-capacity probe survives — it
    cannot change within a process."""
    with _lock:
        _programs.clear()
        _param_trees.clear()
        _opt_trees.clear()
        _unavailable_reported[0] = False
