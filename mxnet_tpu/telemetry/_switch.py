"""The telemetry master switch, in its own module so both halves of the
package (metrics, recorder) and external hot paths (engine) can read
one plain attribute without import cycles.

``enabled`` is initialized from ``MXTPU_TELEMETRY`` once at import;
``telemetry.enable()``/``disable()`` flip it at runtime.  Hot call
sites read it as ``_switch.enabled`` — a single attribute load — which
is the "near-zero cost when disabled" contract.
"""
from __future__ import annotations

from .. import envs

enabled: bool = bool(envs.get("MXTPU_TELEMETRY"))
