"""Flight recorder: a bounded ring of recent runtime events.

The numeric metrics answer "how much"; the flight recorder answers
"what just happened" — the last N structured events (dispatches,
retraces, fallbacks, prefetch stalls, poison) so a crash dump carries
the sequence that led to it, not just final counter values.

* capacity comes from ``MXTPU_FLIGHT_RECORDER_SIZE`` (a ``deque``
  maxlen — appends stay O(1) and old events fall off the far end);
* every event also mirrors into the profiler's chrome-trace stream
  while profiling is active, so ONE timeline shows op spans and
  telemetry events together;
* :func:`dump_flight_recorder` writes the ring (plus a metrics
  snapshot) as a JSON artifact — called automatically when a
  ``CompiledStep`` poisons or ``engine.invoke_compiled`` raises, and on
  demand.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Deque, List, Optional

__all__ = ["record_event", "events", "clear_events",
           "dump_flight_recorder", "auto_dump", "last_dump",
           "note_step", "current_step"]

_lock = threading.Lock()
# TWO rings of equal capacity: high-volume timeline events (dispatch,
# step) would otherwise cycle the ring within a few eager steps and
# evict exactly the events the forensics exist for — a retrace,
# fallback, or poison must survive hundreds of subsequent dispatches.
# events() / dumps merge both by timestamp, so the ONE-timeline view
# is preserved.
_RARE_KINDS = frozenset(("retrace", "fallback", "poison", "error",
                         "evict", "prefetch_stall", "oom_risk",
                         "mem_analysis_unavailable", "health_anomaly",
                         "request_evicted", "slot_oom",
                         "resize", "resize_failed",
                         "hang_suspected", "hang_resolved",
                         "preempted", "preempt_forced",
                         # the silent-corruption sentry's forensics
                         # (docs/elasticity.md, "Integrity sentry"):
                         # a dispatch flood must not evict the proof
                         # that corruption was seen, answered, or
                         # found on disk
                         "corruption_suspected", "corruption_resolved",
                         "device_quarantined", "scrub_corrupt",
                         "integrity_inapplicable",
                         # mxsan (MXL7xx): a use-after-donate or
                         # lock-order finding is forensics a dispatch
                         # flood must not evict
                         "sanitizer_violation",
                         "shed", "deadline_evicted",
                         # recovery answers hang_suspected/poison in the
                         # MXL504 audit and the chaos-soak step
                         # reconciliation — a dispatch flood must not
                         # evict the proof that an owner was healed
                         "recovery"))
_ring: Optional[Deque[dict]] = None        # high-volume kinds
_rare: Optional[Deque[dict]] = None        # retained rare kinds
_dropped = 0          # events pushed out of either ring since clear
_seq = 0              # monotone tiebreak for same-timestamp merging
_step = 0             # completed train steps at event-emit time
_t0 = time.time()
_last_dump: Optional[str] = None
_prof = None          # cached profiler module ref for the mirror
# crash-path dumps are throttled: a test suite that exercises failure
# teleporting would otherwise write one artifact per provoked error
_auto_dumps_left = 25


def _capacity() -> int:
    from .. import envs
    return max(16, envs.get("MXTPU_FLIGHT_RECORDER_SIZE"))


def _get_rings():
    global _ring, _rare
    if _ring is None:
        cap = _capacity()
        _ring = collections.deque(maxlen=cap)
        _rare = collections.deque(maxlen=cap)
    return _ring, _rare


def note_step() -> int:
    """Advance the global train-step counter (called once per
    Trainer/CompiledStep/DataParallelTrainer step, at step END).  An
    event's ``step`` field therefore reads "completed steps when this
    happened": a retrace DURING step N+1 carries ``step: N`` —
    ``analyze_telemetry``'s warm-up filter accounts for that."""
    global _step
    with _lock:
        _step += 1
        return _step


def current_step() -> int:
    return _step


def record_event(kind: str, **fields):
    """Append one structured event (no-op when telemetry is disabled).
    ``kind`` is the taxonomy key (``dispatch``, ``retrace``,
    ``fallback``, ``prefetch_stall``, ``poison``, ``evict``,
    ``error``); fields must be JSON-serializable.  Rare kinds go to
    the retained ring so a flood of dispatch events cannot evict
    them."""
    from . import _switch
    if not _switch.enabled:
        return
    global _dropped, _seq
    with _lock:
        ring, rare = _get_rings()
        target = rare if kind in _RARE_KINDS else ring
        _seq += 1
        ev = {"ts": round(time.time() - _t0, 6), "seq": _seq,
              "kind": kind, "step": _step}
        ev.update(fields)
        if len(target) == target.maxlen:
            _dropped += 1
        target.append(ev)
    # mirror into the chrome-trace stream so profiler timelines show
    # retraces/stalls inline with op spans (only while profiling runs;
    # module ref cached so the per-event cost is one attribute check)
    global _prof
    try:
        if _prof is None:
            from .. import profiler as _p
            _prof = _p
        if _prof.active():
            _prof._mirror_event(f"telemetry:{kind}", fields)
    except Exception:
        pass  # a broken mirror must never take down the recorder


def events(kind: Optional[str] = None) -> List[dict]:
    """Current recorded events (oldest first, both rings merged into
    one timeline), optionally filtered by kind."""
    with _lock:
        ring, rare = _get_rings()
        evs = sorted(list(ring) + list(rare),
                     key=lambda e: e["seq"])
    if kind is not None:
        evs = [e for e in evs if e["kind"] == kind]
    return evs


def clear_events():
    """Empty both rings (capacity re-read from the env on next use, so
    tests can resize it).  The global step counter survives — clearing
    the window between warm-up and a timed region must not make later
    events look like warm-up again."""
    global _ring, _rare, _dropped
    with _lock:
        _ring = None
        _rare = None
        _dropped = 0


def _reset_steps():
    """Zero the global step counter (test isolation; part of
    ``telemetry.reset()``)."""
    global _step
    with _lock:
        _step = 0


def dump_flight_recorder(path: Optional[str] = None,
                         reason: str = "on_demand") -> str:
    """Write the ring + a metrics snapshot as one JSON artifact;
    returns the path written (also readable via :func:`last_dump`).

    Default location: ``MXTPU_TELEMETRY_EXPORT`` when set (created if
    missing), else the system temp dir; filename carries pid + a
    millisecond suffix so concurrent dumps never clobber.
    """
    import tempfile
    from . import metrics
    from .. import envs
    if path is None:
        out_dir = envs.get("MXTPU_TELEMETRY_EXPORT") or \
            tempfile.gettempdir()
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(
            out_dir, "mxtpu_flight_%d_%d.json"
            % (os.getpid(), int(time.time() * 1e3)))
    with _lock:
        ring, rare = _get_rings()
        evs = sorted(list(ring) + list(rare),
                     key=lambda e: e["seq"])
        dropped = _dropped
        step = _step
    artifact = {
        "reason": reason,
        "pid": os.getpid(),
        "wall_time": time.time(),
        "step": step,
        "dropped_events": dropped,
        "events": evs,
        "metrics": metrics.snapshot(),
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(artifact, f, indent=1)
    os.replace(tmp, path)
    global _last_dump
    _last_dump = path
    return path


def auto_dump(reason: str) -> Optional[str]:
    """Crash-path dump (engine error / CompiledStep poison): same as
    :func:`dump_flight_recorder` but throttled per process and never
    raising — forensics must not mask the original failure."""
    global _auto_dumps_left
    from . import _switch
    if not _switch.enabled:
        return None
    with _lock:
        if _auto_dumps_left <= 0:
            return None
        _auto_dumps_left -= 1
    try:
        return dump_flight_recorder(reason=reason)
    except Exception:
        return None


def last_dump() -> Optional[str]:
    """Path of the most recent flight-recorder artifact (or None)."""
    return _last_dump
