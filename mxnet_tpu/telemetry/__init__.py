"""``mxnet_tpu.telemetry``: the runtime observability plane.

PRs 1-3 built a train stack whose performance contract — one dispatch
per step, zero steady-state retraces, a prefetch pipeline that keeps
the device fed — was only checkable in tests.  This package measures
those invariants continuously:

* **metrics** (``telemetry.metrics``): thread-safe counters / gauges /
  fixed-bucket histograms with ``snapshot()``, Prometheus-text and
  JSONL exporters;
* **events + flight recorder** (``telemetry.recorder``): a bounded
  ring of structured events (dispatch, retrace, fallback,
  prefetch_stall, poison, evict, error) dumped to a JSON artifact on
  failure or on demand, and mirrored into the profiler's chrome-trace
  stream while profiling is active;
* **retrace-cause attribution**: the engine and ``CompiledStep`` emit
  ``retrace`` events carrying the exact attr/shape/dtype diff that
  invalidated a cached executable — "op X retraced because
  ``momentum`` changed 0.9 -> 0.5", not "misses went up".

Master switch: ``MXTPU_TELEMETRY`` (default on) /
:func:`enable` / :func:`disable`.  Disabled, every call site pays one
attribute load and returns.  See docs/observability.md for the metric
schema and event taxonomy.
"""
from __future__ import annotations

from . import _switch
from . import metrics
from .metrics import (Counter, Gauge, Histogram, counter, gauge,
                      histogram, snapshot, reset_metrics, to_prometheus,
                      parse_prometheus, write_jsonl, read_jsonl,
                      DEFAULT_LATENCY_BUCKETS)
from .recorder import (record_event, events, clear_events,
                       dump_flight_recorder, auto_dump, last_dump,
                       note_step, current_step)
from . import memory
from . import health

__all__ = [
    "enabled", "enable", "disable", "reset",
    "Counter", "Gauge", "Histogram", "counter", "gauge", "histogram",
    "snapshot", "reset_metrics", "to_prometheus", "parse_prometheus",
    "write_jsonl", "read_jsonl", "DEFAULT_LATENCY_BUCKETS",
    "record_event", "events", "clear_events", "dump_flight_recorder",
    "auto_dump", "last_dump", "note_step", "current_step",
    "record_step", "step_owner", "step_owned",
    "prefetch_stall_ratio", "export_metrics", "memory", "health",
]

#: dispatch-count boundaries for the per-step dispatch histogram: the
#: compiled path is exactly 1; the eager path is O(ops); powers of two
#: keep the regression signature readable.
DISPATCH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)


def enabled() -> bool:
    """Is the telemetry plane recording?"""
    return _switch.enabled


def enable():
    _switch.enabled = True


def disable():
    _switch.enabled = False


def reset():
    """Zero every metric, empty the event ring, rewind the global
    step counter, and forget the memory observatory's harvested
    programs (test isolation / per-run bench hygiene).  Instrument
    identities survive."""
    from . import recorder
    reset_metrics()
    clear_events()
    recorder._reset_steps()
    memory.reset()
    health.reset()


import threading as _threading

_tls = _threading.local()


#: heartbeat hook installed by ``elastic.guardian`` while a Guardian /
#: PreemptionGuard is live: ``(begin(owner, what) -> token,
#: end(token, exc))``.  None (the default) costs one attribute load
#: per step — the guardian plane is pay-for-what-you-watch.
_hb_hook = None


class _StepOwner:
    """Marks the dynamic extent of a WHOLE-step owner (CompiledStep,
    DataParallelTrainer, a serving dispatch bracket): a
    ``Trainer.step`` running inside it records latency only, so the
    step/throughput accounting is done exactly once per real train
    step.  When the owner identifies itself (``owner=``), the bracket
    doubles as the guardian plane's HEARTBEAT: entry registers the
    in-flight step with the hang watchdog, exit clears it (and lets a
    watching ``Guardian`` run its escalation on the owning thread) —
    see ``elastic.guardian``."""

    __slots__ = ("_owner", "_what", "_tok", "_hook")

    def __init__(self, owner=None, what=None):
        self._owner = owner
        self._what = what
        self._tok = None
        self._hook = None

    def __enter__(self):
        _tls.depth = getattr(_tls, "depth", 0) + 1
        hook = _hb_hook
        if hook is not None and self._owner is not None:
            try:
                self._tok = hook[0](self._owner, self._what)
                self._hook = hook
            except Exception:
                self._tok = None   # a broken watchdog never stops a step
        return self

    def __exit__(self, exc_type, exc, tb):
        _tls.depth -= 1
        if self._tok is not None and self._hook is not None:
            # the ENTRY-time hook, not the global: uninstalling the
            # guardian plane mid-step must still clear this bracket's
            # in-flight record, or it leaks and false-flags the next
            # Guardian's first scan as an ancient hang
            try:
                self._hook[1](self._tok, exc)
            except Exception:
                pass               # escalation errors surface as events


def step_owner(owner=None, what: str = None) -> _StepOwner:
    return _StepOwner(owner, what)


def step_owned() -> bool:
    """Is a whole-step owner currently on this thread's stack?"""
    return getattr(_tls, "depth", 0) > 0


def record_step(where: str, seconds: float, dispatches=None,
                examples=None, path: str = None, steps: int = 1):
    """One call records everything a train step owes the telemetry
    plane: latency histogram (per seam — ``compiled_step``,
    ``trainer_step``, ``spmd_step``), the steps counter, the
    dispatches-per-step distribution, and throughput.

    ``dispatches``: engine-dispatch delta across the step — THE
    one-dispatch contract number.  ``path``: which execution path ran
    (``compiled`` / ``eager`` / ``fused`` / ``per_param``), kept as a
    field on the step event so the flight recorder shows path flips.
    ``steps``: real optimizer steps in this call (``step_multi(K)``
    passes K) — the steps counter advances by it, and a bulked call's
    wall time lands in a separate ``..._bulk_seconds`` histogram so
    the per-step latency distribution stays a distribution of
    measured single steps.
    """
    if not _switch.enabled:
        return
    step = None
    for _ in range(max(1, int(steps))):
        step = note_step()
    suffix = "_seconds" if steps <= 1 else "_bulk_seconds"
    histogram(f"mxtpu_{where}{suffix}",
              f"{where} wall-clock latency (s)"
              + ("" if steps <= 1 else ", per bulked multi-step call")
              ).observe(seconds)
    counter("mxtpu_steps_total", "train steps recorded").inc(
        max(1, int(steps)))
    fields = {"where": where, "seconds": round(seconds, 6)}
    if steps > 1:
        fields["bulked_steps"] = int(steps)
    if path is not None:
        fields["path"] = path
    if dispatches is not None:
        fields["dispatches"] = dispatches
        if steps <= 1:
            # per-step contract numbers only from single-step calls: a
            # bulked call's 1 dispatch covers K steps and would read
            # as a (wrong) per-step value
            gauge("mxtpu_last_step_dispatches",
                  "engine dispatches in the most recent step"
                  ).set(dispatches)
            histogram("mxtpu_step_dispatches",
                      "engine dispatches per train step",
                      buckets=DISPATCH_BUCKETS).observe(dispatches)
    if examples:
        counter("mxtpu_examples_total", "training examples consumed"
                ).inc(examples)
        if seconds > 0:
            gauge("mxtpu_examples_per_sec",
                  "throughput of the most recent step"
                  ).set(examples / seconds)
    record_event("step", **fields)
    return step


def prefetch_stall_ratio() -> float:
    """Fraction of consumed batches on which the consumer found the
    prefetch queue dry (input-bound signature); 0.0 before any loader
    ran."""
    snap = snapshot()["counters"]
    batches = snap.get("mxtpu_dataloader_batches_total", 0.0)
    if not batches:
        return 0.0
    return snap.get("mxtpu_prefetch_stalls_total", 0.0) / batches


def export_metrics(path: str = None) -> str:
    """Append a JSONL metrics snapshot to ``path`` (default:
    ``metrics.jsonl`` under ``MXTPU_TELEMETRY_EXPORT`` or the cwd);
    returns the path written."""
    import os
    from .. import envs
    if path is None:
        out_dir = envs.get("MXTPU_TELEMETRY_EXPORT") or "."
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir, "metrics.jsonl")
    write_jsonl(path)
    return path
