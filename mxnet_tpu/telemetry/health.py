"""Training-health plane: in-graph numerics monitoring + host sentinel.

The telemetry plane (PR 4) proves the PERFORMANCE contract — one
dispatch per step, zero steady-state retraces — but says nothing about
whether the numbers coming out of that one dispatch are any good: a
diverging run (loss spike, gradient explosion, a NaN from a bad batch)
burns a full chip window before a human reads a loss curve.  This
module watches the numerics continuously, without breaking the
contracts the rest of the stack fought for:

* **in-graph stats** — :func:`compute` runs INSIDE the compiled step
  trace (``gluon.CompiledStep`` and the SPMD
  ``DataParallelTrainer``'s fused step splice it in) and returns one
  flat f32 vector as an extra program output: loss, global grad norm,
  global nonfinite count, and per-top-level-subtree param/grad/update
  norms + nonfinite counts.  Monitoring therefore costs ZERO extra
  dispatches — the one-dispatch contract holds with health on;
* **sampled host transfer** — the device vector is read back only
  every ``MXTPU_HEALTH_EVERY`` steps (the read is the only host sync
  the plane adds; at the default K=10 it is <1% of step time on the
  CPU smoke, see bench.py's ``health`` block);
* **host sentinel** — :class:`Sentinel` keeps rolling loss/grad-norm
  statistics per step owner and emits retained ``health_anomaly``
  flight-recorder events (loss spike, grad-norm explosion,
  update-ratio collapse, any nonfinite) with SUBTREE attribution, in
  the style of PR 4's retrace-cause attribution;
* **actions** (``MXTPU_HEALTH_ACTION``) — ``warn`` records only;
  ``skip`` bakes a nonfinite gate into the traced step
  (:func:`gate`): a step whose gradients carry any nonfinite value
  writes the OLD params/optimizer state back out, so one poisoned
  batch cannot corrupt the donated training state; ``rollback``
  drives the elastic plane's ``recover(manager)`` protocol on a
  nonfinite or sustained-divergence verdict, restoring the last
  committed checkpoint (docs/elasticity.md) — the loop PR 7 left
  open.

Everything is inert under ``MXTPU_TELEMETRY=0`` or ``MXTPU_HEALTH=0``:
the traced program is then byte-identical to a health-less build (no
extra outputs), and the host pays one attribute check per step.  The
action and subtree layout are part of the traced program, so they ride
the persist identity / ``_check_sig`` eviction seams — flipping
``MXTPU_HEALTH*`` mid-process retraces ONCE with an attributed cause
instead of silently serving a stale program.  See
docs/observability.md ("Training health").
"""
from __future__ import annotations

import collections
import json
import math
import threading
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["enabled", "every", "action", "trace_signature", "build_spec",
           "HealthSpec", "compute", "gate", "due_flags", "Sentinel",
           "get_sentinel",
           "sample_owner", "handle_verdict", "sentinels", "report",
           "dump_report", "render_table", "reset", "poison_inputs",
           "UPDATE_RATIO_BUCKETS"]

#: update-ratio (||delta w|| / ||w||) distribution boundaries: healthy
#: SGD sits around 1e-3; the decades below catch collapse, above catch
#: blow-up.
UPDATE_RATIO_BUCKETS = (1e-7, 1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 1e-1, 1.0)

_GLOBAL_FIELDS = ("loss", "grad_norm", "nonfinite")
_SUBTREE_FIELDS = ("param_norm", "grad_norm", "update_norm", "nonfinite")


# -- configuration (env-driven; re-read per call so tests/operators can
# flip knobs at runtime — the step stacks detect the flip through
# trace_signature() and retrace once, with attribution) ----------------

def enabled() -> bool:
    """Is the health plane recording?  Requires BOTH the telemetry
    master switch and ``MXTPU_HEALTH``."""
    from . import _switch
    if not _switch.enabled:
        return False
    from .. import envs
    return bool(envs.get("MXTPU_HEALTH"))


def every() -> int:
    """Host sampling period K (``MXTPU_HEALTH_EVERY``): the device
    health vector is read back on every K-th train step."""
    from .. import envs
    return max(1, int(envs.get("MXTPU_HEALTH_EVERY")))


def action() -> str:
    """``warn`` | ``skip`` | ``rollback`` (``MXTPU_HEALTH_ACTION``;
    unknown values degrade to ``warn`` — a typo'd knob must not change
    the traced program silently)."""
    from .. import envs
    act = str(envs.get("MXTPU_HEALTH_ACTION")).strip().lower()
    return act if act in ("warn", "skip", "rollback") else "warn"


def _window() -> int:
    from .. import envs
    return max(4, int(envs.get("MXTPU_HEALTH_WINDOW")))


def _patience() -> int:
    from .. import envs
    return max(1, int(envs.get("MXTPU_HEALTH_PATIENCE")))


def trace_signature() -> Optional[tuple]:
    """What the TRACED program bakes from this module: None when the
    plane is off (no extra outputs), else ``("health", version,
    skip_gate_active)``.  The step stacks fold this into their
    signature/persist identity so a config flip evicts the stale
    executable instead of mis-unpacking its outputs."""
    if not enabled():
        return None
    return ("health", 1, action() == "skip")


# -- spec: the health vector's layout ---------------------------------

class HealthSpec:
    """Layout of one step's health vector.

    ``fields()`` names every slot: 3 globals (``loss``, ``grad_norm``,
    ``nonfinite``) then 4 per top-level subtree
    (``<subtree>.param_norm/grad_norm/update_norm/nonfinite``) — plus,
    when the integrity sentry is armed (``elastic.integrity``), the
    per-dp-replica fingerprint pairs its cross-replica agreement
    audit reads.  ``groups`` maps each subtree to positions in the
    TRAINABLE param list (the j-indices the step stacks use for
    tvals/grads/new values), so attribution points at the exact child
    block.
    """

    __slots__ = ("subtrees", "groups", "skip", "integrity")

    def __init__(self, subtrees: List[str], groups: List[List[int]],
                 skip: bool, integrity=None):
        self.subtrees = list(subtrees)
        self.groups = [list(g) for g in groups]
        self.skip = bool(skip)
        #: optional ``elastic.integrity.IntegritySpec`` — its slot
        #: rows ride the TAIL of this vector (the step builders append
        #: them after :func:`compute`'s numerics section)
        self.integrity = integrity

    @property
    def base_n(self) -> int:
        """Slot count of the numerics section — what :func:`compute`
        builds (the integrity rows are appended by the step builder)."""
        return len(_GLOBAL_FIELDS) + \
            len(_SUBTREE_FIELDS) * len(self.subtrees)

    @property
    def n(self) -> int:
        return self.base_n + (self.integrity.slots
                              if self.integrity is not None else 0)

    def fields(self) -> List[str]:
        out = list(_GLOBAL_FIELDS)
        for s in self.subtrees:
            out.extend(f"{s}.{f}" for f in _SUBTREE_FIELDS)
        if self.integrity is not None:
            out.extend(self.integrity.fields())
        return out

    def signature(self) -> tuple:
        """Structural identity (part of the step's persist/sig hash):
        the subtree layout, the skip gate, and the integrity layout
        are all baked into the traced program."""
        return ("health", 1, self.skip, tuple(self.subtrees),
                tuple(tuple(g) for g in self.groups)) + (
                    (self.integrity.signature(),)
                    if self.integrity is not None else ())

    def parse(self, vec) -> dict:
        """Host-side view of one sampled vector: globals + a per-
        subtree dict (+ the per-replica fingerprints when armed)."""
        import numpy as np
        v = np.asarray(vec, dtype=np.float64).reshape(-1)
        if v.shape[0] != self.n:
            raise ValueError(
                f"health vector has {v.shape[0]} slots, spec expects "
                f"{self.n}")
        out = {k: float(v[i]) for i, k in enumerate(_GLOBAL_FIELDS)}
        subs = {}
        off = len(_GLOBAL_FIELDS)
        for s in self.subtrees:
            subs[s] = {f: float(v[off + i])
                       for i, f in enumerate(_SUBTREE_FIELDS)}
            off += len(_SUBTREE_FIELDS)
        out["subtrees"] = subs
        if self.integrity is not None:
            out["integrity"] = self.integrity.parse(v[off:])
        return out


def _subtree_of(name: str, prefix: str) -> str:
    """Top-level subtree of a param name: the first path component
    after the net's own prefix (gluon names are flat,
    ``netX_childY_weight``)."""
    if prefix and name.startswith(prefix):
        name = name[len(prefix):]
    name = name.lstrip("_")
    head, _, rest = name.partition("_")
    # "dense0_weight" -> "dense0"; a bare "weight" (param directly on
    # the net) groups under its own name
    return head if rest else name


def build_spec(prefix: str, param_names: Sequence[str],
               integrity=None) -> Optional[HealthSpec]:
    """Build the health layout for one step owner, or None when the
    plane is off.  ``param_names`` are the TRAINABLE params in the
    order the step passes tvals/grads (position j in that list is the
    group index).  ``integrity``: an
    ``elastic.integrity.IntegritySpec`` for owners with a >1 dp axis
    (the SPMD trainer) — its fingerprint rows ride this vector's
    tail."""
    if not enabled():
        return None
    order: List[str] = []
    groups: Dict[str, List[int]] = {}
    for j, name in enumerate(param_names):
        s = _subtree_of(str(name), prefix or "")
        if s not in groups:
            groups[s] = []
            order.append(s)
        groups[s].append(j)
    return HealthSpec(order, [groups[s] for s in order],
                      skip=action() == "skip", integrity=integrity)


# -- traced computation ------------------------------------------------

def _compute_full(spec: HealthSpec, loss_val, old_tvals, grads,
                  new_tvals):
    import jax.numpy as jnp

    def _sq(x):
        return jnp.sum(jnp.square(x.astype(jnp.float32)))

    return _compute_from_sq(spec, loss_val, old_tvals,
                            [_sq(g) for g in grads], new_tvals)


def _compute_from_sq(spec: HealthSpec, loss_val, old_tvals, g_sq,
                     new_tvals):
    import jax.numpy as jnp

    def _sq(x):
        return jnp.sum(jnp.square(x.astype(jnp.float32)))

    # nonfinite DETECTION rides the squared sums the norms need
    # anyway: any NaN/Inf in a gradient poisons its sum, so
    # ~isfinite(sum) flags the subtree with ZERO extra passes over the
    # tensors (an explicit isfinite scan measured ~40% of the whole
    # health cost).  A finite-but-enormous gradient whose square
    # overflows f32 also flags — a grad norm past 1.8e19 is divergence
    # by any name.  Slots are therefore 0/1 indicators per subtree;
    # the global slot counts flagged subtrees (+1 for a nonfinite
    # loss), keeping the "> 0 means poisoned" contract.
    def _bad(s):
        return (~jnp.isfinite(s)).astype(jnp.float32)

    loss_mean = jnp.mean(loss_val.astype(jnp.float32))
    sub_slots = []
    bad_total = _bad(loss_mean)
    for g in spec.groups:
        g2 = sum(g_sq[j] for j in g)
        bad_s = _bad(g2)
        bad_total = bad_total + bad_s
        sub_slots.append([
            jnp.sqrt(sum(_sq(old_tvals[j]) for j in g)),
            jnp.sqrt(g2),
            jnp.sqrt(sum(_sq(new_tvals[j] - old_tvals[j])
                         for j in g)),
            bad_s])
    slots = [loss_mean, jnp.sqrt(sum(g_sq)), bad_total]
    for row in sub_slots:
        slots.extend(row)
    return jnp.stack(slots)


def compute(spec: HealthSpec, loss_val, old_tvals, grads, new_tvals,
            due=None):
    """Build the health vector INSIDE a step trace.

    ``loss_val``: the (possibly unreduced) loss value; ``old_tvals`` /
    ``new_tvals``: trainable param values before/after the optimizer
    update; ``grads``: their gradients — all aligned with the spec's
    group indices.  Returns a 1-D f32 array of ``spec.n`` slots.

    ``due`` is the DYNAMIC sampling flag (a 0-d f32 program input, 1.0
    on sampled steps): the reductions run under ``lax.cond``, so the
    ~P element passes they cost are paid only every
    ``MXTPU_HEALTH_EVERY`` steps — on a CPU/memory-bound step the
    always-on cost would dwarf the update itself.  With the skip gate
    armed the stats are needed EVERY step (the gate reads the
    nonfinite count), so ``spec.skip`` computes unconditionally; a
    ``None`` due does too (callers without a sampling schedule).
    """
    if due is None or spec.skip:
        return _compute_full(spec, loss_val, old_tvals, grads,
                             new_tvals)
    import jax.numpy as jnp
    from jax import lax
    return lax.cond(
        due > 0,
        lambda: _compute_full(spec, loss_val, old_tvals, grads,
                              new_tvals),
        lambda: jnp.zeros((spec.base_n,), jnp.float32))


def compute_sharded(spec: HealthSpec, loss_val, old_tvals, g_sq,
                    new_tvals, due=None):
    """:func:`compute` for a step whose full gradients NEVER
    materialize (the ZeRO-2 reduce-scatter path, docs/zero.md):
    ``g_sq`` holds the per-trainable-param GLOBAL squared gradient
    sums, which the step derives from its scattered slices plus ONE
    (T,)-vector psum — ``sum over members of sum(slice**2)`` equals
    the full gradient's squared sum exactly, so every slot (norms,
    nonfinite flags, attribution) matches the replicated computation
    while the gradient wire stays reduce-scatter.  Same ``due``/skip
    semantics as :func:`compute`."""
    if due is None or spec.skip:
        return _compute_from_sq(spec, loss_val, old_tvals, g_sq,
                                new_tvals)
    import jax.numpy as jnp
    from jax import lax
    return lax.cond(
        due > 0,
        lambda: _compute_from_sq(spec, loss_val, old_tvals, g_sq,
                                 new_tvals),
        lambda: jnp.zeros((spec.base_n,), jnp.float32))


def due_flags(base: int, k: int):
    """Host-side sampling schedule for the next ``k`` steps after
    ``base`` completed ones: a (k,) f32 of 0/1 flags matching
    :func:`sample_owner`'s read-back decision (step ``base + i + 1``
    is sampled when it hits the ``MXTPU_HEALTH_EVERY`` boundary)."""
    import numpy as np
    ev = every()
    return np.asarray([1.0 if (base + i + 1) % ev == 0 else 0.0
                       for i in range(k)], np.float32)


def gate(health_vec, new_vals, old_vals):
    """The in-graph ``skip`` action: when the health vector saw any
    nonfinite (slot 2 > 0), every updated value is replaced by its
    pre-step original — the poisoned update becomes a no-op on the
    donated training state (loss output still reports the bad step).
    Identity when the step is healthy, so warn-mode parity is exact.
    """
    import jax.numpy as jnp
    bad = health_vec[2] > 0
    return tuple(jnp.where(bad, o, n) for n, o in
                 zip(new_vals, old_vals))


def gate_update(health_vec, new_params, old_params, new_states,
                old_states, aux, old_aux):
    """The skip gate over a fused step's whole update — params,
    per-param optimizer-state tuples, and forward-mutated aux — so
    both SPMD step bodies carry the invariant from ONE place (the
    compressed variant adds residual gating on top)."""
    new_params = gate(health_vec, new_params, old_params)
    new_states = tuple(
        tuple(gate(health_vec, sn, so))
        for sn, so in zip(new_states, old_states))
    aux = gate(health_vec, aux, old_aux)
    return new_params, new_states, aux


# -- deterministic nonfinite injection (docs/elasticity.md grammar) ----

def poison_inputs(args, ctx=None):
    """Plant a NaN in the leading element of each input batch — the
    ``nonfinite_grad`` fault point's payload (``MXTPU_FAULT_INJECT=
    nonfinite_grad:step=N``).  A NaN input propagates through forward/
    backward to a nonfinite loss and gradients, which is exactly the
    numerics failure the sentinel, the skip gate, and the rollback
    protocol must catch; shapes/dtypes are unchanged so nothing
    retraces."""
    import numpy as np
    from .. import ndarray as nd
    out = []
    poisoned = False
    for a in args:
        host = a.asnumpy().copy()
        if host.size and np.issubdtype(host.dtype, np.floating):
            host.reshape(-1)[0] = np.nan
            poisoned = True
        out.append(nd.array(host, dtype=host.dtype,
                            ctx=ctx or getattr(a, "context", None)))
    if not poisoned:
        # integer-only inputs (embedding-first nets): NaN cannot ride
        # them, and the one-shot spec is already consumed — say so
        # loudly instead of letting a drill "fire" while doing nothing
        from .recorder import record_event
        record_event("fault_injected", point="nonfinite_grad",
                     noop=True,
                     reason="no floating-point input to poison")
    return out


# -- host sentinel ------------------------------------------------------

class Sentinel:
    """Rolling-statistics watchdog over one step owner's samples.

    ``observe(vec, step)`` parses a sampled health vector, updates the
    gauges/counters, appends to the bounded history, and returns a
    VERDICT dict when action is warranted — ``kind`` is ``nonfinite``
    (immediate) or ``divergence`` (``patience`` consecutive anomalous
    samples).  Each individual anomaly (loss spike, grad explosion,
    update-ratio collapse, nonfinite) emits one retained
    ``health_anomaly`` flight-recorder event with subtree attribution.

    Baselines are ROBUST: anomalous samples never enter the rolling
    windows, so one spike cannot drag the mean up and mask the next.
    """

    #: loss > mean + LOSS_SIGMA * std of the rolling window
    LOSS_SIGMA = 6.0
    #: grad norm > GRAD_FACTOR * rolling median
    GRAD_FACTOR = 10.0
    #: mean update ratio < COLLAPSE_FACTOR * rolling median
    COLLAPSE_FACTOR = 1e-3
    #: rolling windows must hold this many samples before spike/
    #: explosion/collapse verdicts arm (nonfinite always fires)
    MIN_SAMPLES = 8
    #: bounded per-owner history backing report()/tools/mxhealth.py
    HISTORY = 256

    def __init__(self, spec: HealthSpec, where: str):
        self.spec = spec
        self.where = where
        self._lock = threading.Lock()
        win = _window()
        self._loss_win = collections.deque(maxlen=win)
        self._grad_win = collections.deque(maxlen=win)
        self._ratio_win = collections.deque(maxlen=win)
        self._history = collections.deque(maxlen=self.HISTORY)
        self._anomalies = collections.deque(maxlen=self.HISTORY)
        self._streak = 0
        self.last_verdict: Optional[dict] = None
        self.samples = 0

    # -- helpers -------------------------------------------------------
    @staticmethod
    def _median(win) -> float:
        s = sorted(win)
        n = len(s)
        return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])

    def _worst_subtree(self, parsed: dict, field: str) -> Optional[str]:
        subs = parsed.get("subtrees") or {}
        best, best_v = None, -math.inf
        for name, row in subs.items():
            v = row.get(field, 0.0)
            if math.isfinite(v) and v > best_v:
                best, best_v = name, v
        return best

    def _mean_ratio(self, parsed: dict) -> Optional[float]:
        """Mean ||update|| / ||param|| over subtrees with nonzero
        params — the per-step learning-signal size."""
        ratios = []
        for row in (parsed.get("subtrees") or {}).values():
            p = row.get("param_norm", 0.0)
            if p > 0 and math.isfinite(p) and \
                    math.isfinite(row.get("update_norm", 0.0)):
                ratios.append(row["update_norm"] / p)
        return sum(ratios) / len(ratios) if ratios else None

    # -- the sample path -----------------------------------------------
    def observe(self, vec, step: Optional[int] = None,
                skipped: Optional[bool] = None) -> Optional[dict]:
        """Ingest one sampled health vector; returns the verdict (or
        None).  ``skipped`` marks whether the in-graph skip gate was
        armed for this step (action=skip), purely for event fields."""
        from . import _switch
        if not _switch.enabled:
            return None
        from . import metrics as _m
        from .recorder import record_event, current_step
        parsed = self.spec.parse(vec)
        if step is None:
            step = current_step()
        if skipped is None:
            skipped = self.spec.skip
        loss, gnorm = parsed["loss"], parsed["grad_norm"]
        nonfinite = parsed["nonfinite"]
        ratio = self._mean_ratio(parsed)

        _m.counter("mxtpu_health_samples_total",
                   "health vectors read back from the device").inc()
        _m.gauge("mxtpu_health_loss",
                 "loss at the most recent health sample").set(
            loss if math.isfinite(loss) else float("nan"))
        _m.gauge("mxtpu_health_grad_norm",
                 "global gradient norm at the most recent health "
                 "sample").set(gnorm if math.isfinite(gnorm)
                               else float("nan"))
        if ratio is not None and math.isfinite(ratio):
            _m.histogram(
                "mxtpu_health_update_ratio",
                "per-sample mean ||update||/||param|| over subtrees",
                buckets=UPDATE_RATIO_BUCKETS).observe(ratio)
        if nonfinite > 0:
            _m.counter(
                "mxtpu_health_nonfinite_total",
                "nonfinite values observed in sampled loss/gradients"
                ).inc(nonfinite)

        anomalies: List[dict] = []
        # cross-replica integrity audit (elastic.integrity): replicated
        # values must agree across the dp axis — a minority replica is
        # the corruption suspect, attributed by device index.  Checked
        # BEFORE the numerics branches: a bitflip usually stays finite
        # and would otherwise pass every norm check silently.
        integ = parsed.get("integrity")
        if integ:
            from ..elastic import integrity as _integrity
            for row in ("param", "grad"):
                fps = integ.get(f"{row}_fp")
                if not fps:
                    continue
                suspects = _integrity.agreement(fps)
                if suspects is None:
                    continue
                anomalies.append({
                    "anomaly": "integrity_divergence",
                    "row": row, "suspects": suspects,
                    "subtrees": [],
                    "detail": (f"{row} fingerprints diverge across "
                               f"the dp axis; suspect device(s) "
                               f"{suspects} "
                               f"(fps: "
                               f"{[f'{v:08x}' for v in fps]})")})
                _integrity.note_suspected(self.where, row, suspects,
                                          fps, int(step))
        with self._lock:
            armed = len(self._loss_win) >= self.MIN_SAMPLES
            if nonfinite > 0 or not math.isfinite(loss) or \
                    not math.isfinite(gnorm):
                bad_subs = sorted(
                    s for s, row in parsed["subtrees"].items()
                    if row["nonfinite"] > 0)
                anomalies.append({
                    "anomaly": "nonfinite",
                    "count": int(nonfinite),
                    "subtrees": bad_subs,
                    "detail": (f"{int(nonfinite)} nonfinite value(s) in "
                               "loss/gradients"
                               + (f"; subtree(s) {', '.join(bad_subs)}"
                                  if bad_subs else ""))})
            else:
                if armed:
                    mean = sum(self._loss_win) / len(self._loss_win)
                    var = sum((x - mean) ** 2 for x in self._loss_win) \
                        / len(self._loss_win)
                    std = math.sqrt(var)
                    bound = mean + self.LOSS_SIGMA * max(
                        std, 1e-8 + 1e-3 * abs(mean))
                    if loss > bound:
                        anomalies.append({
                            "anomaly": "loss_spike", "value": loss,
                            "bound": bound,
                            "subtrees": [self._worst_subtree(
                                parsed, "grad_norm")],
                            "detail": f"loss {loss:.6g} above rolling "
                                      f"bound {bound:.6g} (mean "
                                      f"{mean:.6g} + {self.LOSS_SIGMA}"
                                      "*std)"})
                    gmed = self._median(self._grad_win)
                    if gmed > 0 and gnorm > self.GRAD_FACTOR * gmed:
                        anomalies.append({
                            "anomaly": "grad_explosion", "value": gnorm,
                            "bound": self.GRAD_FACTOR * gmed,
                            "subtrees": [self._worst_subtree(
                                parsed, "grad_norm")],
                            "detail": f"grad norm {gnorm:.6g} is "
                                      f"{gnorm / gmed:.1f}x the rolling "
                                      f"median {gmed:.6g}"})
                    if ratio is not None and self._ratio_win:
                        rmed = self._median(self._ratio_win)
                        if rmed > 0 and \
                                ratio < self.COLLAPSE_FACTOR * rmed:
                            anomalies.append({
                                "anomaly": "update_ratio_collapse",
                                "value": ratio,
                                "bound": self.COLLAPSE_FACTOR * rmed,
                                "subtrees": [self._worst_subtree(
                                    parsed, "param_norm")],
                                "detail":
                                    f"update ratio {ratio:.3g} "
                                    "collapsed vs rolling median "
                                    f"{rmed:.3g}"})
                if not anomalies:
                    # only healthy samples feed the baselines
                    self._loss_win.append(loss)
                    self._grad_win.append(gnorm)
                    if ratio is not None:
                        self._ratio_win.append(ratio)
            if anomalies:
                self._streak += 1
            else:
                self._streak = 0
            streak = self._streak
            self.samples += 1
            row = dict(parsed)
            row["step"] = int(step)
            # the ratio THE DETECTOR USED (isfinite-guarded), so the
            # report never shows a different number than the verdict
            # was judged against
            row["update_ratio"] = ratio
            row["anomalies"] = [a["anomaly"] for a in anomalies]
            self._history.append(row)

        for a in anomalies:
            _m.counter("mxtpu_health_anomalies_total",
                       "health anomalies the sentinel flagged").inc()
            record_event("health_anomaly", where=self.where,
                         skipped=bool(skipped and
                                      a["anomaly"] == "nonfinite"),
                         **a)

        verdict = None
        integ_anoms = [a for a in anomalies
                       if a["anomaly"] == "integrity_divergence"]
        if integ_anoms:
            # immediate, like nonfinite — and ranked above it: a
            # bitflip that ALSO went nonfinite is still a corruption
            # incident first (the response ladder differs).  The
            # streak rides along so handle_verdict can fall through
            # to the HEALTH ladder when an unactioned (warn-mode)
            # corruption verdict co-occurs with sustained numerics
            # anomalies.
            suspects = sorted({s for a in integ_anoms
                               for s in a["suspects"]})
            verdict = {"kind": "integrity_divergence",
                       "suspects": suspects, "streak": streak,
                       "anomalies": anomalies, "step": int(step)}
        elif any(a["anomaly"] == "nonfinite" for a in anomalies):
            verdict = {"kind": "nonfinite", "anomalies": anomalies,
                       "step": int(step)}
        elif anomalies and streak >= _patience():
            verdict = {"kind": "divergence", "streak": streak,
                       "anomalies": anomalies, "step": int(step)}
        with self._lock:
            if verdict is not None:
                self.last_verdict = verdict
            # under the lock: snapshot() iterates this deque from
            # other threads (live report renders)
            for a in anomalies:
                self._anomalies.append(dict(a, step=int(step)))
        return verdict

    # -- reporting -----------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "where": self.where,
                "fields": self.spec.fields(),
                "subtrees": list(self.spec.subtrees),
                "skip_gate": self.spec.skip,
                "samples": self.samples,
                "history": [dict(r) for r in self._history],
                "anomalies": [dict(a) for a in self._anomalies],
                "last_verdict": self.last_verdict,
            }


def sample_owner(owner, where: str, spec: HealthSpec, health_out,
                 k: int = 1) -> Optional[dict]:
    """The shared per-dispatch sampling path for the step stacks.

    Advances ``owner._health_count`` by the dispatch's ``k`` real
    steps, and ONLY when a sampled index (every ``MXTPU_HEALTH_EVERY``
    steps) landed in this dispatch reads the device vector back (the
    plane's one host sync), feeds the owner's sentinel, and applies
    the verdict action.  ``health_out`` is the raw program output — a
    1-D vector for a single step, a (K, n) matrix for a bulked
    ``step_multi``.  Returns the verdict, if any."""
    import numpy as np
    base = getattr(owner, "_health_count", 0)
    owner._health_count = base + k
    ev = every()
    due = [i for i in range(k) if (base + i + 1) % ev == 0]
    if not due:
        return None
    sent = get_sentinel(where, spec)
    mat = np.asarray(health_out)
    # each row keeps ITS step index (owner-local, 1-based) so a bulked
    # dispatch's anomalies localize to the exact inner step
    rows = [(base + 1, mat)] if mat.ndim == 1 else \
        [(base + i + 1, mat[i]) for i in due]
    verdict = None
    for step_i, r in rows:
        v = sent.observe(r, step=step_i)
        if v is not None:
            verdict = v
    handle_verdict(owner, verdict)
    return verdict


def handle_verdict(owner, verdict: Optional[dict]) -> bool:
    """The action half of a sentinel verdict: under
    ``MXTPU_HEALTH_ACTION=rollback`` with a manager attached
    (``owner.health_manager``), a nonfinite or divergence verdict
    drives the owner's ``recover(manager)`` — the elastic plane's
    restore-from-last-committed-checkpoint protocol.  Returns True
    when a rollback ran.  ``skip`` needs no host action (the gate is
    in-graph); ``warn`` records only.  An ``integrity_divergence``
    verdict takes the corruption ladder instead
    (``MXTPU_INTEGRITY_ACTION`` — warn / rollback / QUARANTINE,
    ``elastic.integrity.respond``)."""
    if verdict is None:
        return False
    if verdict.get("kind") == "integrity_divergence":
        from ..elastic import integrity as _integrity
        if _integrity.respond(owner, verdict):
            return True
        others = [a for a in verdict.get("anomalies", ())
                  if a.get("anomaly") != "integrity_divergence"]
        nonfinite = any(a.get("anomaly") == "nonfinite"
                        for a in others)
        diverging = others and \
            int(verdict.get("streak", 0)) >= _patience()
        if not (nonfinite or diverging):
            return False
        # the sample ALSO carried numerics anomalies the health
        # ladder would have acted on (nonfinite, or a sustained
        # spike/explosion/collapse streak past patience): an
        # unactioned corruption verdict (warn mode) must not
        # suppress the user's configured MXTPU_HEALTH_ACTION —
        # fall through to it
    if action() != "rollback":
        return False
    manager = getattr(owner, "health_manager", None)
    if manager is None:
        from .recorder import record_event
        record_event("health_anomaly", where="health",
                     anomaly="rollback_unarmed",
                     detail="MXTPU_HEALTH_ACTION=rollback but no "
                            "health_manager is attached; set "
                            "owner.health_manager to a "
                            "CheckpointManager")
        return False
    try:
        owner.recover(manager)
    except Exception as e:
        # armed but nothing committed yet (or the restore itself
        # died): degrade LOUDLY like the unarmed case instead of
        # crashing the training loop — the sentinel keeps flagging and
        # retrying on every sampled verdict until a save commits
        from .recorder import record_event
        record_event("health_anomaly", where="health",
                     anomaly="rollback_failed",
                     detail=f"recover(manager) failed: {e!r}"[:300])
        return False
    # counted AFTER the restore: a failed recover must not read as a
    # rollback that happened
    from . import metrics as _m
    _m.counter("mxtpu_health_rollbacks_total",
               "automatic checkpoint rollbacks on a health verdict"
               ).inc()
    return True


# -- per-process registry (tools/mxhealth.py / bench read it) ----------

_reg_lock = threading.Lock()
_sentinels: Dict[str, Sentinel] = {}


def get_sentinel(where: str, spec: HealthSpec) -> Sentinel:
    """The step stacks register here so one process-wide report covers
    every owner.  A spec change (retrace after a config flip) replaces
    the sentinel — stale windows from a different layout would
    misparse."""
    with _reg_lock:
        s = _sentinels.get(where)
        if s is None or s.spec.signature() != spec.signature():
            s = Sentinel(spec, where)
            _sentinels[where] = s
        return s


def sentinels() -> Dict[str, Sentinel]:
    with _reg_lock:
        return dict(_sentinels)


def reset():
    """Forget every sentinel (test isolation; part of
    ``telemetry.reset()``)."""
    with _reg_lock:
        _sentinels.clear()


def report() -> dict:
    """Process-wide health report: one entry per step owner, plus the
    plane's config."""
    return {
        "kind": "mxtpu_health_report",
        "enabled": enabled(),
        "every": every(),
        "action": action(),
        "owners": {w: s.snapshot() for w, s in sentinels().items()},
    }


def dump_report(path: str) -> str:
    """Write :func:`report` as a JSON artifact (atomic); returns the
    path — ``tools/mxhealth.py render`` displays it."""
    import os
    rep = report()
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(rep, f, indent=1, default=str)
    os.replace(tmp, path)
    return path


def render_table(rep: dict, last: int = 12) -> str:
    """Text rendering of a :func:`report` dict: per-owner rolling
    health table (last N samples), the anomaly log, and the last
    verdict — the ``tools/mxhealth.py`` view."""
    lines = [f"health plane: enabled={rep.get('enabled')} "
             f"every={rep.get('every')} action={rep.get('action')}"]
    owners = rep.get("owners") or {}
    if not owners:
        lines.append("no health samples recorded")
        return "\n".join(lines)
    for where, snap in sorted(owners.items()):
        lines.append("")
        lines.append(f"[{where}] {snap.get('samples', 0)} sample(s), "
                     f"subtrees: {', '.join(snap.get('subtrees', []))}"
                     + (" (skip gate armed)"
                        if snap.get("skip_gate") else ""))
        hist = (snap.get("history") or [])[-last:]
        lines.append(f"{'STEP':>6} {'LOSS':>12} {'GRAD':>12} "
                     f"{'RATIO':>10} {'NONFIN':>7} ANOMALIES")
        for row in hist:
            ratio = row.get("update_ratio")
            if ratio is None:
                ratio = float("nan")
            lines.append(
                f"{row.get('step', 0):>6} {row.get('loss', 0):>12.5g} "
                f"{row.get('grad_norm', 0):>12.5g} {ratio:>10.3g} "
                f"{int(row.get('nonfinite', 0)):>7} "
                f"{','.join(row.get('anomalies') or []) or '-'}")
        anomalies = snap.get("anomalies") or []
        if anomalies:
            lines.append("anomaly log:")
            for a in anomalies[-last:]:
                subs = ", ".join(x for x in (a.get("subtrees") or [])
                                 if x)
                lines.append(
                    f"  step {a.get('step', 0)}: {a.get('anomaly')} "
                    f"[{subs or 'global'}] {a.get('detail', '')}")
        v = snap.get("last_verdict")
        lines.append(f"last verdict: "
                     + (f"{v['kind']} at step {v.get('step')}"
                        if v else "healthy"))
    return "\n".join(lines)
