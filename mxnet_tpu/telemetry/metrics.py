"""Structured runtime metrics: counters, gauges, histograms.

The registry is the numeric half of the telemetry plane (events are the
other half, ``recorder.py``).  Design constraints, in order:

* **near-zero when disabled** — every mutating call checks the module
  switch (a plain attribute read) before touching a lock, so a process
  running with ``MXTPU_TELEMETRY=0`` pays one branch per call site;
* **thread-safe** — DataLoader workers, the consumer thread, and the
  train loop all record concurrently; one registry lock serializes
  mutations (instrument updates are a few arithmetic ops, so a single
  lock does not contend measurably);
* **fixed histogram buckets** — bucket boundaries are part of an
  instrument's identity, chosen at creation and never resized, so two
  snapshots are always comparable and the Prometheus exposition is
  stable across a process's lifetime.

Exporters: :func:`snapshot` (point-in-time dict), :func:`to_prometheus`
(text exposition format) and :func:`write_jsonl` / :func:`read_jsonl`
(one JSON object per instrument per line — the append-friendly format
the bench trajectory files consume).
"""
from __future__ import annotations

import json
import math
import threading
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "counter", "gauge",
           "histogram", "snapshot", "reset_metrics", "to_prometheus",
           "parse_prometheus", "write_jsonl", "read_jsonl",
           "DEFAULT_LATENCY_BUCKETS"]

_lock = threading.Lock()
_instruments: Dict[str, "_Instrument"] = {}

#: step-latency boundaries (seconds): 100 us .. 2 min, roughly
#: geometric.  Wide enough for a sub-ms fused MLP step AND a bulked
#: BERT-base dispatch through a remote tunnel.
DEFAULT_LATENCY_BUCKETS = (
    1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2, 0.1, 0.3, 1.0, 3.0, 10.0,
    30.0, 120.0)


def _enabled() -> bool:
    # late import: the switch lives on the package root so one flag
    # gates metrics AND events; this indirection only runs on the
    # mutation paths, which already decided to do work
    from . import _switch
    return _switch.enabled


class _Instrument:
    """Shared identity (name, doc, kind); subclasses hold the value."""

    kind = "instrument"
    __slots__ = ("name", "doc")

    def __init__(self, name: str, doc: str = ""):
        self.name = name
        self.doc = doc

    def _sample(self):
        raise NotImplementedError

    def _reset(self):
        raise NotImplementedError


class Counter(_Instrument):
    """Monotonically increasing count (dispatches, stalls, retraces)."""

    kind = "counter"
    __slots__ = ("_value",)

    def __init__(self, name, doc=""):
        super().__init__(name, doc)
        self._value = 0.0

    def inc(self, amount: float = 1.0):
        if not _enabled():
            return
        if amount < 0:
            raise ValueError(
                f"counter {self.name!r} cannot decrease (inc {amount})")
        with _lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value

    def _sample(self):
        return {"type": "counter", "name": self.name, "value": self._value}

    def _reset(self):
        self._value = 0.0


class Gauge(_Instrument):
    """Point-in-time level (queue depth, staging occupancy)."""

    kind = "gauge"
    __slots__ = ("_value",)

    def __init__(self, name, doc=""):
        super().__init__(name, doc)
        self._value = 0.0

    def set(self, value: float):
        if not _enabled():
            return
        with _lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0):
        if not _enabled():
            return
        with _lock:
            self._value += amount

    def dec(self, amount: float = 1.0):
        self.inc(-amount)

    @property
    def value(self) -> float:
        return self._value

    def _sample(self):
        return {"type": "gauge", "name": self.name, "value": self._value}

    def _reset(self):
        self._value = 0.0


class Histogram(_Instrument):
    """Distribution over FIXED bucket boundaries.

    ``buckets`` are upper bounds (``le``); an implicit +inf bucket
    catches the tail.  ``observe`` is O(len(buckets)) worst case —
    bisect would save nothing at these sizes and keeps the hot path
    allocation-free.
    """

    kind = "histogram"
    __slots__ = ("buckets", "_counts", "_count", "_sum", "_min", "_max")

    def __init__(self, name, doc="", buckets: Sequence[float] = None):
        super().__init__(name, doc)
        bounds = tuple(float(b) for b in
                       (buckets if buckets is not None
                        else DEFAULT_LATENCY_BUCKETS))
        if list(bounds) != sorted(bounds) or len(set(bounds)) != len(bounds):
            raise ValueError(
                f"histogram {name!r} buckets must be strictly "
                f"increasing, got {bounds}")
        self.buckets = bounds
        self._counts = [0] * (len(bounds) + 1)   # +1: the +inf bucket
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf

    def observe(self, value: float):
        if not _enabled():
            return
        v = float(value)
        with _lock:
            i = 0
            for b in self.buckets:
                if v <= b:
                    break
                i += 1
            self._counts[i] += 1
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    def summary(self) -> dict:
        """Aggregate view: count/sum/min/max/avg plus cumulative bucket
        counts — the shape the bench telemetry block embeds."""
        with _lock:
            counts = list(self._counts)
            n, s = self._count, self._sum
            mn, mx = self._min, self._max
        cumulative: List[Tuple[float, int]] = []
        acc = 0
        for b, c in zip(self.buckets, counts):
            acc += c
            cumulative.append((b, acc))
        return {"count": n, "sum": s,
                "min": mn if n else None, "max": mx if n else None,
                "avg": (s / n) if n else None,
                "buckets": cumulative}

    def quantile(self, q: float):
        """Prometheus-style quantile estimate from the cumulative
        bucket counts: the upper bound of the first bucket whose
        cumulative count reaches ``q`` of the total, clamped to the
        observed min/max (so p50/p99 of a tight distribution do not
        report a coarse bucket edge beyond the real range).  ``None``
        before any observation."""
        with _lock:
            counts = list(self._counts)
            n = self._count
            mn, mx = self._min, self._max
        if not n:
            return None
        rank = q * n
        acc = 0
        for b, c in zip(self.buckets, counts):
            acc += c
            if acc >= rank:
                return min(max(b, mn), mx)
        return mx

    def _sample(self):
        d = self.summary()
        d.update(type="histogram", name=self.name,
                 buckets=[[b, c] for b, c in d["buckets"]])
        return d

    def _reset(self):
        self._counts = [0] * (len(self.buckets) + 1)
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf


def _get_or_create(cls, name, doc, **kw):
    with _lock:
        inst = _instruments.get(name)
        if inst is None:
            inst = cls(name, doc, **kw)
            _instruments[name] = inst
        elif not isinstance(inst, cls):
            raise TypeError(
                f"metric {name!r} already registered as {inst.kind}, "
                f"requested {cls.kind}")
        return inst


def counter(name: str, doc: str = "") -> Counter:
    """Get or create the named counter (idempotent — call sites don't
    coordinate registration order)."""
    return _get_or_create(Counter, name, doc)


def gauge(name: str, doc: str = "") -> Gauge:
    return _get_or_create(Gauge, name, doc)


def histogram(name: str, doc: str = "",
              buckets: Optional[Sequence[float]] = None) -> Histogram:
    return _get_or_create(Histogram, name, doc, buckets=buckets)


def snapshot() -> dict:
    """Point-in-time view of every instrument, grouped by kind."""
    with _lock:
        insts = list(_instruments.values())
    out = {"counters": {}, "gauges": {}, "histograms": {}}
    for inst in insts:
        if inst.kind == "counter":
            out["counters"][inst.name] = inst.value
        elif inst.kind == "gauge":
            out["gauges"][inst.name] = inst.value
        else:
            out["histograms"][inst.name] = inst.summary()
    return out


def reset_metrics():
    """Zero every instrument (identity/buckets retained) — for tests
    and per-run bench isolation."""
    with _lock:
        for inst in _instruments.values():
            inst._reset()


# -- exporters --------------------------------------------------------------

def to_prometheus() -> str:
    """Prometheus text exposition (0.0.4) of the current registry."""
    with _lock:
        insts = sorted(_instruments.values(), key=lambda i: i.name)
    lines: List[str] = []
    for inst in insts:
        if inst.doc:
            lines.append(f"# HELP {inst.name} {inst.doc}")
        lines.append(f"# TYPE {inst.name} {inst.kind}")
        if inst.kind == "counter":
            # Prometheus counters end in _total; don't double the
            # suffix when the instrument already follows the convention
            n = inst.name if inst.name.endswith("_total") \
                else inst.name + "_total"
            lines.append(f"{n} {inst.value:g}")
        elif inst.kind == "gauge":
            lines.append(f"{inst.name} {inst.value:g}")
        else:
            s = inst.summary()
            for b, c in s["buckets"]:
                lines.append(f'{inst.name}_bucket{{le="{b:g}"}} {c}')
            lines.append(f'{inst.name}_bucket{{le="+Inf"}} {s["count"]}')
            lines.append(f"{inst.name}_sum {s['sum']:g}")
            lines.append(f"{inst.name}_count {s['count']}")
    return "\n".join(lines) + "\n"


def parse_prometheus(text: str) -> dict:
    """Parse :func:`to_prometheus` output back into
    ``{name: value-or-series}`` — the round-trip half the exporter test
    (and any scraper-less consumer) uses."""
    out: Dict[str, object] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name_part, _, value = line.rpartition(" ")
        if "{" in name_part:
            base, _, label = name_part.partition("{")
            le = label.rstrip("}").split("=", 1)[1].strip('"')
            series = out.setdefault(base, {})
            series[le] = float(value)
        else:
            out[name_part] = float(value)
    return out


def write_jsonl(path: str) -> int:
    """Append one JSON line per instrument to ``path``; returns the
    number of lines written."""
    with _lock:
        insts = sorted(_instruments.values(), key=lambda i: i.name)
    rows = [inst._sample() for inst in insts]
    with open(path, "a") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")
    return len(rows)


def read_jsonl(path: str) -> List[dict]:
    """Load every sample row from a :func:`write_jsonl` file."""
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(json.loads(line))
    return rows
