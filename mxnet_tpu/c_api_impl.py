"""Python-side implementation of the flat C API.

Capability parity: reference ``src/c_api/c_api.cc`` + ``c_api_ndarray.cc``
+ ``c_api_symbolic.cc`` + ``c_api_executor.cc`` (SURVEY.md §2.1 "C API").
The C++ layer in ``src/c_api.cc`` embeds CPython, holds opaque handles
(PyObject*), and marshals flat C types; every function here takes/returns
only simple Python types so the C++ side stays thin.  Op parameters
arrive as STRINGS and are parsed here — the same contract as the
reference's ``MXImperativeInvokeEx``, whose param values are strings
parsed by dmlc::Parameter.

The TPU-native story: a non-Python frontend (C, C++, any FFI-capable
language) drives the SAME XLA compute path as the Python frontend — the
embedded interpreter is the runtime, XLA executes everything.
"""
from __future__ import annotations

import ast
import json
import os

import numpy as np

# honor JAX_PLATFORMS for embedded (non-Python-launched) consumers: the
# axon PJRT plugin re-registers itself over the env var on import, so the
# platform must be pinned through jax.config before any backend init
# (same workaround as tests/conftest.py)
if os.environ.get("JAX_PLATFORMS"):
    import jax
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

_DTYPE_CODES = {0: "float32", 1: "float64", 2: "float16", 3: "uint8",
                4: "int32", 5: "int8", 6: "int64", 7: "bool",
                12: "bfloat16"}
_DTYPE_NAMES = {v: k for k, v in _DTYPE_CODES.items()}


def _dtype_name(code: int) -> str:
    try:
        return _DTYPE_CODES[code]
    except KeyError:
        raise ValueError(f"unknown dtype code {code}")


def dtype_code(name) -> int:
    return _DTYPE_NAMES[np.dtype(name).name if name != "bfloat16"
                        else "bfloat16"]


def _parse_param(v: str):
    """Parse a string-valued op param (reference: dmlc::Parameter)."""
    s = v.strip()
    low = s.lower()
    if low in ("true", "false"):
        return low == "true"
    if low in ("none", "null"):
        return None
    try:
        return ast.literal_eval(s)
    except (ValueError, SyntaxError):
        return s


# -- NDArray ----------------------------------------------------------------

def _ctx(ctx_type: int, ctx_id: int):
    """ctx codes (include/mxtpu/c_api.h): 1=cpu 2=tpu."""
    import mxnet_tpu as mx
    if ctx_type == 1:
        return mx.cpu(ctx_id)
    if ctx_type == 2:
        return mx.tpu(ctx_id)
    raise ValueError(f"unknown ctx_type {ctx_type}")


def ndarray_create(shape, dtype_code_, ctx_type, ctx_id):
    from mxnet_tpu import nd
    return nd.zeros(tuple(shape), ctx=_ctx(ctx_type, ctx_id),
                    dtype=_dtype_name(dtype_code_))


def ndarray_from_bytes(shape, dtype_code_, data: bytes, ctx_type, ctx_id):
    from mxnet_tpu import nd
    a = np.frombuffer(data, dtype=_dtype_name(dtype_code_)).reshape(
        tuple(shape)).copy()
    return nd.array(a, ctx=_ctx(ctx_type, ctx_id), dtype=a.dtype)


def ndarray_to_bytes(arr) -> bytes:
    return np.ascontiguousarray(arr.asnumpy()).tobytes()


def ndarray_shape(arr):
    return list(arr.shape)


def ndarray_dtype(arr) -> int:
    return _DTYPE_NAMES[np.dtype(arr.dtype).name]


def ndarray_wait(arr):
    arr.wait_to_read()


def ndarray_copy(arr):
    return arr.copy()


def waitall():
    from mxnet_tpu import nd
    nd.waitall()


# -- imperative invoke ------------------------------------------------------

def imperative_invoke(op_name: str, inputs, keys, vals):
    """Invoke a registered op by name; returns a list of NDArrays."""
    from mxnet_tpu.ops.registry import get_op
    from mxnet_tpu.ndarray.ndarray import invoke
    kwargs = {k: _parse_param(v) for k, v in zip(keys, vals)}
    out = invoke(get_op(op_name), list(inputs), **kwargs)
    if isinstance(out, (list, tuple)):
        return list(out)
    return [out]


def list_ops():
    from mxnet_tpu.ops.registry import list_ops as _lo
    return sorted(_lo())


# -- Symbol -----------------------------------------------------------------

def symbol_create_variable(name: str):
    from mxnet_tpu import sym
    return sym.Variable(name)


def symbol_from_json(js: str):
    from mxnet_tpu.symbol.symbol import load_json
    return load_json(js)


def symbol_to_json(s) -> str:
    return s.tojson()


def symbol_list_arguments(s):
    return list(s.list_arguments())


def symbol_list_outputs(s):
    return list(s.list_outputs())


def symbol_list_aux(s):
    return list(s.list_auxiliary_states())


def symbol_infer_shape_json(s, shapes_json: str) -> str:
    """Input: {"name": [dims...]} known shapes; output JSON with
    arg_shapes/out_shapes/aux_shapes."""
    known = {k: tuple(v) for k, v in json.loads(shapes_json).items()}
    arg, out, aux = s.infer_shape(**known)
    return json.dumps({
        "arg_shapes": [list(x) for x in (arg or [])],
        "out_shapes": [list(x) for x in (out or [])],
        "aux_shapes": [list(x) for x in (aux or [])],
    })


def symbol_invoke(op_name: str, in_syms, in_names, name, keys, vals):
    """Symbolic compose of a registered op (reference:
    MXSymbolCreateAtomicSymbol + Compose)."""
    from mxnet_tpu import sym as sym_mod
    kwargs = {k: _parse_param(v) for k, v in zip(keys, vals)}
    op = getattr(sym_mod, op_name)
    pos = list(in_syms)
    if in_names and len(in_names) == len(pos):
        for n, s in zip(in_names, pos):
            kwargs[n] = s
        pos = []
    if name:
        kwargs["name"] = name
    return op(*pos, **kwargs)


# -- Executor ---------------------------------------------------------------

def executor_simple_bind_json(s, shapes_json: str, ctx_type, ctx_id,
                              grad_req: str):
    shapes = {k: tuple(v) for k, v in json.loads(shapes_json).items()}
    return s.simple_bind(ctx=_ctx(ctx_type, ctx_id), grad_req=grad_req,
                         **shapes)


def executor_arg_dict(ex):
    return ex.arg_dict


def executor_set_arg(ex, name: str, arr):
    ex.arg_dict[name][:] = arr


def executor_forward(ex, is_train: int):
    ex.forward(is_train=bool(is_train))
    return list(ex.outputs)


def executor_backward(ex, head_grads):
    ex.backward(head_grads if head_grads else None)


def executor_grad(ex, name: str):
    return ex.grad_dict[name]


# -- KVStore ----------------------------------------------------------------

def kvstore_create(kv_type: str):
    from mxnet_tpu import kv
    return kv.create(kv_type)


def kvstore_init(kvs, key: int, arr):
    kvs.init(key, arr)


def kvstore_push(kvs, key: int, arr):
    kvs.push(key, arr)


def kvstore_pull(kvs, key: int, out):
    kvs.pull(key, out=out)


# -- misc -------------------------------------------------------------------

def random_seed(seed: int):
    import mxnet_tpu as mx
    mx.random.seed(seed)


def num_tpus() -> int:
    import mxnet_tpu as mx
    return mx.num_tpus()


# -- Predict API (deploy surface) -------------------------------------------
# Parity: reference src/c_api/c_predict_api.cc + include/mxnet/c_predict_api.h
# (SURVEY.md §2.1 "C API": "predict API is a minimal deploy surface").
# A predictor = exported symbol JSON + params blob bound for inference.

class _Predictor:
    def __init__(self, symbol_json, param_bytes, ctx_type, ctx_id,
                 input_names, input_shapes):
        from mxnet_tpu import nd
        from mxnet_tpu import symbol as sym_mod
        self._sym = sym_mod.load_json(symbol_json)
        params = nd.load_buffer(param_bytes) if param_bytes else {}
        if not isinstance(params, dict):
            raise ValueError(
                "predictor params blob must be name->array (saved via "
                "nd.save(path, dict) / Block.export), got an unnamed "
                "list")
        clean = {}
        for k, v in params.items():
            clean[k[4:] if k.startswith(("arg:", "aux:")) else k] = v
        shapes = {n: tuple(int(d) for d in s)
                  for n, s in zip(input_names, input_shapes)}
        self._ex = self._sym.simple_bind(
            ctx=_ctx(ctx_type, ctx_id), grad_req="null", **shapes)
        for name, arr in clean.items():
            if name in self._ex.arg_dict:
                self._ex.arg_dict[name][:] = arr
            elif name in self._ex.aux_dict:
                self._ex.aux_dict[name][:] = arr
        self._input_names = list(input_names)
        self._outputs = None
        # static output shapes so MXPredGetOutputShape works BEFORE the
        # first forward (the canonical c_predict_api buffer-sizing flow)
        try:
            _, self._static_out_shapes, _ = self._sym.infer_shape(**shapes)
        except Exception:
            self._static_out_shapes = None

    def set_input(self, key, data_bytes):
        if key not in self._input_names:
            raise KeyError(
                f"{key!r} is not a declared input "
                f"(inputs: {self._input_names}); parameters cannot be "
                "overwritten through MXPredSetInput")
        arr = self._ex.arg_dict[key]
        np_arr = np.frombuffer(data_bytes, dtype="float32").reshape(
            arr.shape)
        arr[:] = np_arr

    def forward(self):
        self._outputs = self._ex.forward(is_train=False)

    def output_shape(self, index):
        if self._outputs is not None:
            return tuple(int(d) for d in self._outputs[index].shape)
        if self._static_out_shapes is None:
            raise RuntimeError("output shape unavailable before forward "
                               "(shape inference failed at bind time)")
        return tuple(int(d) for d in self._static_out_shapes[index])

    def get_output(self, index):
        if self._outputs is None:
            self.forward()
        return self._outputs[index].astype("float32").asnumpy().tobytes()


def pred_create(symbol_json, param_bytes, ctx_type, ctx_id,
                input_names, input_shapes):
    return _Predictor(symbol_json, param_bytes, ctx_type, ctx_id,
                      input_names, input_shapes)


def pred_set_input(p, key, data_bytes):
    p.set_input(key, data_bytes)


def pred_forward(p):
    p.forward()


def pred_output_shape(p, index):
    return p.output_shape(index)


def pred_get_output(p, index):
    return p.get_output(index)
