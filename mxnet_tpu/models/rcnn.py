"""Faster R-CNN two-stage detector (capability target: reference
``example/rcnn`` + GluonCV ``faster_rcnn`` family — SURVEY.md §2.6).

TPU-first design: both stages are STATIC-shape so the whole train step
compiles to one XLA program —
- the RPN proposes a FIXED number of regions per image (top-K by
  objectness over the dense anchor grid; the classic dynamic
  NMS-then-threshold pipeline survives only in ``decode``, where the
  framework NMS marks suppressed rows instead of dropping them);
- RoI features come from the framework ``ROIAlign`` (batched, static
  K rois per image);
- target assignment for both stages is dense IoU matrices + argmax
  selection (no scatter, no dynamic box lists), the same recipe as
  models/yolo.py;
- proposals are gradient-blocked before RoIAlign (standard two-stage
  training: the head does not backprop through box coordinates).
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..gluon.block import HybridBlock
from ..gluon import nn

__all__ = ["FasterRCNN", "FasterRCNNLoss", "faster_rcnn_tiny"]


def _conv_bn_relu(channels, stride=1, prefix=""):
    out = nn.HybridSequential(prefix=prefix)
    with out.name_scope():
        out.add(nn.Conv2D(channels, 3, strides=stride, padding=1,
                          use_bias=False),
                nn.BatchNorm(), nn.Activation("relu"))
    return out


def _encode_deltas(nd, src, dst):
    """Box regression targets src→dst, both (..., 4) corner px."""
    sw = nd.maximum(src[..., 2] - src[..., 0], nd.ones_like(src[..., 0]))
    sh = nd.maximum(src[..., 3] - src[..., 1], nd.ones_like(src[..., 0]))
    sx = (src[..., 0] + src[..., 2]) / 2.0
    sy = (src[..., 1] + src[..., 3]) / 2.0
    dw = nd.maximum(dst[..., 2] - dst[..., 0], nd.ones_like(src[..., 0]))
    dh = nd.maximum(dst[..., 3] - dst[..., 1], nd.ones_like(src[..., 0]))
    dx = (dst[..., 0] + dst[..., 2]) / 2.0
    dy = (dst[..., 1] + dst[..., 3]) / 2.0
    return nd.stack((dx - sx) / sw, (dy - sy) / sh,
                    nd.log(dw / sw), nd.log(dh / sh), axis=-1)


def _apply_deltas(nd, boxes, deltas, size):
    """Inverse of _encode_deltas, clipped to the image."""
    bw = nd.maximum(boxes[..., 2] - boxes[..., 0],
                    nd.ones_like(boxes[..., 0]))
    bh = nd.maximum(boxes[..., 3] - boxes[..., 1],
                    nd.ones_like(boxes[..., 0]))
    bx = (boxes[..., 0] + boxes[..., 2]) / 2.0
    by = (boxes[..., 1] + boxes[..., 3]) / 2.0
    cx = bx + deltas[..., 0] * bw
    cy = by + deltas[..., 1] * bh
    w = bw * nd.exp(nd.clip(deltas[..., 2], -4.0, 4.0))
    h = bh * nd.exp(nd.clip(deltas[..., 3], -4.0, 4.0))
    out = nd.stack(cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2,
                   axis=-1)
    return nd.clip(out, 0.0, float(size))


class FasterRCNN(HybridBlock):
    """Two-stage detector with a fixed proposal budget.

    ``forward(x)`` returns (rpn_obj (B, Na), rpn_deltas (B, Na, 4),
    proposals (B, K, 4) px corner, cls_logits (B, K, C+1),
    head_deltas (B, K, 4)); class 0 is background.
    """

    def __init__(self, num_classes, image_size=64, base_channels=16,
                 anchor_sizes=(12, 24, 40), num_proposals=16,
                 roi_size=4, **kwargs):
        super().__init__(**kwargs)
        if image_size % 8:
            raise MXNetError("image_size must be a multiple of 8")
        self.num_classes = num_classes
        self._size = image_size
        self._stride = 8
        self._k = int(num_proposals)
        self._roi = int(roi_size)
        g = image_size // self._stride
        # dense centered anchors: one square per size per cell
        ys, xs = np.mgrid[0:g, 0:g].astype("f4")
        cxy = np.stack([xs, ys], -1).reshape(-1, 2) * self._stride \
            + self._stride / 2.0
        anchors = []
        for s in anchor_sizes:
            anchors.append(np.concatenate(
                [cxy - s / 2.0, cxy + s / 2.0], axis=1))
        # slot order: (anchor size, cell) — matches the head reshape
        self._anchors_np = np.concatenate(anchors, 0).astype("f4")
        self._num_anchor_shapes = len(anchor_sizes)
        with self.name_scope():
            # constant param: under hybridize the anchors ride the
            # params mechanism instead of closing over a live NDArray
            self.anchors_c = self.params.get_constant(
                "anchors", self._anchors_np)
            self.backbone = nn.HybridSequential(prefix="backbone_")
            with self.backbone.name_scope():
                self.backbone.add(_conv_bn_relu(base_channels))
                self.backbone.add(_conv_bn_relu(base_channels * 2, 2))
                self.backbone.add(_conv_bn_relu(base_channels * 4, 2))
                self.backbone.add(_conv_bn_relu(base_channels * 8, 2))
            self.rpn_conv = _conv_bn_relu(base_channels * 8,
                                          prefix="rpnc_")
            a = self._num_anchor_shapes
            self.rpn_obj = nn.Conv2D(a, 1, prefix="rpno_")
            self.rpn_box = nn.Conv2D(a * 4, 1, prefix="rpnb_")
            self.head_fc = nn.Dense(128, activation="relu",
                                    flatten=False, prefix="fc_")
            self.head_cls = nn.Dense(num_classes + 1, flatten=False,
                                     prefix="cls_")
            self.head_box = nn.Dense(4, flatten=False, prefix="box_")

    @property
    def num_anchors(self):
        return self._anchors_np.shape[0]

    def hybrid_forward(self, F, x, anchors_c=None):
        b = x.shape[0]
        feat = self.backbone(x)
        r = self.rpn_conv(feat)
        a = self._num_anchor_shapes
        g2 = feat.shape[2] * feat.shape[3]
        obj = self.rpn_obj(r).reshape((b, a * g2))         # (B, Na)
        deltas = self.rpn_box(r).reshape((b, a, 4, g2))
        deltas = deltas.transpose((0, 1, 3, 2)).reshape(
            (b, a * g2, 4))                                # (B, Na, 4)

        boxes = _apply_deltas(F, anchors_c.expand_dims(0), deltas,
                              self._size)                  # (B, Na, 4)
        # fixed proposal budget: top-K objectness, gradient-blocked
        k = self._k
        top_idx = F.topk(obj, k=k, axis=-1)                # (B, K)
        props = F.stop_gradient(
            _take_rows(F, boxes, top_idx))                 # (B, K, 4)

        # RoIAlign over the batch: rois (B*K, 5) with batch index
        bidx = F.repeat(F.arange(0, b, ctx=x.context)
                        .reshape((b, 1)), repeats=k, axis=1)
        rois = F.concat(bidx.reshape((b * k, 1)),
                        props.reshape((b * k, 4)), dim=-1)
        pooled = F.ROIAlign(
            feat, rois, pooled_size=(self._roi, self._roi),
            spatial_scale=1.0 / self._stride)              # (BK,C,r,r)
        h = self.head_fc(pooled.reshape((b, k, -1)))
        return (obj, deltas, props, self.head_cls(h),
                self.head_box(h))

    def decode(self, outs, conf_thresh=0.05, nms_thresh=0.5):
        """(B, K, 6) [cls_id, score, x1, y1, x2, y2] in [0, 1] with
        suppressed rows -1 (framework NMS); background excluded."""
        from .. import ndarray as nd
        _, _, props, cls_logits, head_deltas = outs
        probs = nd.softmax(cls_logits, axis=-1)            # (B,K,C+1)
        fg = probs[:, :, 1:]
        cls_id = nd.argmax(fg, axis=-1, keepdims=True)
        score = nd.max(fg, axis=-1, keepdims=True)
        boxes = _apply_deltas(nd, props, head_deltas, self._size) \
            / float(self._size)
        rows = nd.concat(cls_id.astype("float32"), score, boxes,
                         dim=-1)
        return nd.contrib.box_nms(
            rows, overlap_thresh=nms_thresh, valid_thresh=conf_thresh,
            topk=self._k, id_index=0, score_index=1, coord_start=2)


def _take_rows(nd, data, idx):
    """data (B, N, D), idx (B, K) → (B, K, D) without scatter: one-hot
    select (K x N matmul), static shapes."""
    n = data.shape[1]
    onehot = nd.one_hot(idx.astype("int32"), n)            # (B, K, N)
    return nd.batch_dot(onehot, data)


class FasterRCNNLoss:
    """RPN BCE + smooth-L1 and head CE + smooth-L1, with dense-IoU
    target assignment (pos ≥ ``rpn_pos_iou``/``head_pos_iou``, RPN
    negatives < ``rpn_neg_iou``, in-between ignored).  ``labels`` are
    SSD-style (B, M, 5) [cls, x1..y2] in [0, 1], pad cls = -1."""

    def __init__(self, net: FasterRCNN, rpn_pos_iou=0.5,
                 rpn_neg_iou=0.3, head_pos_iou=0.5):
        self.net = net
        self.rpn_pos = float(rpn_pos_iou)
        self.rpn_neg = float(rpn_neg_iou)
        self.head_pos = float(head_pos_iou)

    def __call__(self, outs, labels):
        from .. import ndarray as nd
        net = self.net
        size = float(net._size)
        obj, deltas, props, cls_logits, head_deltas = outs
        b, m = labels.shape[0], labels.shape[1]
        valid = (labels[:, :, 0:1] >= 0)                   # (B, M, 1)
        gt_boxes = labels[:, :, 1:] * size                 # (B, M, 4)
        gt_cls = nd.maximum(labels[:, :, 0],
                            nd.zeros_like(labels[:, :, 0]))

        def match(boxes):
            """(B, X, 4) → (iou_best (B, X), best_gt_idx (B, X))."""
            iou = nd.contrib.box_iou(boxes, gt_boxes) \
                * valid.transpose((0, 2, 1))               # (B, X, M)
            return nd.max(iou, axis=-1), nd.argmax(iou, axis=-1)

        def gather_gt(field, idx):
            """field (B, M, D), idx (B, X) → (B, X, D)."""
            return _take_rows(nd, field, idx)

        def bce(logit, target):
            return nd.relu(logit) - logit * target + \
                nd.log(1.0 + nd.exp(-nd.abs(logit)))

        def smooth_l1(x):
            ax = nd.abs(x)
            return nd.where(ax > 1.0, ax - 0.5, 0.5 * x * x)

        # ---- RPN stage ----------------------------------------------
        anchors = net.anchors_c.data(obj.context).expand_dims(0)
        anc = nd.broadcast_to(anchors, (b,) + anchors.shape[1:])
        a_iou, a_gt = match(anc)
        pos = (a_iou >= self.rpn_pos)
        neg = (a_iou < self.rpn_neg)
        npos = nd.maximum(nd.sum(pos), nd.ones((1,), ctx=obj.context))
        rpn_obj_loss = nd.sum(
            bce(obj, pos) * (pos + neg)) / nd.maximum(
                nd.sum(pos + neg), nd.ones((1,), ctx=obj.context))
        t = _encode_deltas(nd, anc, gather_gt(gt_boxes, a_gt))
        rpn_box_loss = nd.sum(
            smooth_l1(deltas - t) * pos.expand_dims(-1)) / npos

        # ---- head stage ---------------------------------------------
        p_iou, p_gt = match(props)
        fg = (p_iou >= self.head_pos)                      # (B, K)
        cls_target = (gather_gt(gt_cls.expand_dims(-1),
                                p_gt)[:, :, 0] + 1.0) * fg  # 0 = bg
        logp = nd.log_softmax(cls_logits, axis=-1)
        head_cls_loss = -nd.mean(
            nd.pick(logp, cls_target.astype("int32"), axis=-1))
        th = _encode_deltas(nd, props, gather_gt(gt_boxes, p_gt))
        nfg = nd.maximum(nd.sum(fg), nd.ones((1,), ctx=obj.context))
        head_box_loss = nd.sum(
            smooth_l1(head_deltas - th) * fg.expand_dims(-1)) / nfg

        return (rpn_obj_loss + rpn_box_loss + head_cls_loss
                + head_box_loss)


def faster_rcnn_tiny(num_classes=2, image_size=64, **kwargs):
    """Test-size Faster R-CNN (64px, 8x8 grid, 16 proposals)."""
    return FasterRCNN(num_classes, image_size=image_size,
                      base_channels=8, **kwargs)
