"""Backbone feature truncation shared by the dense-prediction heads
(segmentation, pose): strip a zoo classification net's ``features``
down to its convolutional stages."""
from __future__ import annotations

from ..base import MXNetError

_HEAD_LAYERS = ("GlobalAvgPool2D", "Flatten", "Dropout", "Dense")


def truncate_features(zoo_net, reject_dense=True):
    """Return the conv-stage blocks of ``zoo_net.features``.

    Trailing classifier layers (global pool / flatten / dropout, and
    Dense when ``reject_dense`` is False) are stripped.  With
    ``reject_dense`` True, a Dense INSIDE the remaining features
    (vgg/alexnet-style) raises — those backbones flatten mid-stream
    and cannot provide spatial taps."""
    blocks = list(zoo_net.features._children.values())
    strip = _HEAD_LAYERS if not reject_dense else _HEAD_LAYERS[:-1]
    while blocks and blocks[-1].__class__.__name__ in strip:
        blocks = blocks[:-1]
    if len(blocks) < 3:
        raise MXNetError("backbone too shallow for dense prediction")
    if reject_dense and any(
            b.__class__.__name__ == "Dense" for b in blocks):
        raise MXNetError(
            "backbone features contain Dense layers (vgg/alexnet "
            "style); dense-prediction taps need a fully-convolutional "
            "backbone such as the resnet/mobilenet/densenet zoos")
    return blocks
