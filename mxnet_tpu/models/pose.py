"""Pose estimation: SimplePose heatmap regression (capability target:
GluonCV ``simple_pose_resnet*`` — SURVEY.md §2.6 external zoos).

SimplePose (Xiao et al.) = classification backbone truncated at the
stride-32 features + three stride-2 deconvolution stages + a 1x1 head
producing one heatmap per keypoint; training regresses Gaussian target
heatmaps with an L2 loss masked by keypoint visibility; decoding takes
the per-heatmap argmax (with the classic quarter-pixel offset toward
the second-highest neighbor omitted — argmax is exact on the synthetic
tasks and keeps decode a single compiled program).

TPU notes: deconvs are MXU-shaped convs; the whole train step fuses
under hybridize(); decode is argmax + unravel, no host loop.
"""
from __future__ import annotations

import numpy as np

from ..gluon.block import HybridBlock
from ..gluon import nn
from ..metric import EvalMetric
from .feature import truncate_features

__all__ = ["SimplePose", "PoseHeatmapLoss", "gaussian_heatmaps",
           "PCKMetric", "simple_pose_tiny"]


class SimplePose(HybridBlock):
    """Backbone stages + deconv head + per-keypoint heatmap layer.

    ``backbone`` is a fully-convolutional zoo net (classifier head
    ignored); the heatmap resolution is input/4 with the standard
    three stride-2 deconvs over stride-32 features."""

    def __init__(self, num_keypoints, backbone, deconv_channels=64,
                 num_deconv=3, **kwargs):
        super().__init__(**kwargs)
        self.num_keypoints = num_keypoints
        with self.name_scope():
            self._backbone = truncate_features(backbone,
                                               reject_dense=False)
            for i, b in enumerate(self._backbone):
                self.register_child(b, f"bb{i}")
            self.deconv = nn.HybridSequential(prefix="deconv_")
            with self.deconv.name_scope():
                for _ in range(num_deconv):
                    self.deconv.add(
                        nn.Conv2DTranspose(deconv_channels, 4,
                                           strides=2, padding=1,
                                           use_bias=False),
                        nn.BatchNorm(),
                        nn.Activation("relu"))
            self.head = nn.Conv2D(num_keypoints, 1, prefix="head_")

    def hybrid_forward(self, F, x):
        for b in self._backbone:
            x = b(x)
        return self.head(self.deconv(x))        # (B, K, H', W')

    def predict(self, x):
        """Keypoint coords in [0, 1]: (B, K, 2) as (x, y)."""
        from .. import ndarray as nd
        hm = self(x)
        b, k, h, w = hm.shape
        flat = hm.reshape((b, k, h * w))
        idx = nd.argmax(flat, axis=-1)           # (B, K)
        ys = nd.floor(idx / w)
        xs = idx - ys * w
        # heatmap-cell centers, normalized by the heatmap size
        return nd.stack((xs + 0.5) / w, (ys + 0.5) / h, axis=-1)


def gaussian_heatmaps(keypoints, heatmap_size, sigma=1.5):
    """(B, K, 3) [x, y, visible] in [0,1] → (B, K, H, W) float32
    Gaussian targets (numpy; targets are data, not model)."""
    kp = np.asarray(keypoints, "f4")
    b, k, _ = kp.shape
    h = w = int(heatmap_size)
    ys, xs = np.mgrid[0:h, 0:w].astype("f4") + 0.5
    out = np.zeros((b, k, h, w), "f4")
    for i in range(b):
        for j in range(k):
            x, y, v = kp[i, j]
            if v <= 0:
                continue
            d2 = (xs - x * w) ** 2 + (ys - y * h) ** 2
            out[i, j] = np.exp(-d2 / (2.0 * sigma ** 2))
    return out


class PoseHeatmapLoss:
    """Visibility-masked L2 between predicted and target heatmaps."""

    def __call__(self, pred, target, visible):
        from .. import ndarray as nd
        diff = (pred - target) ** 2              # (B, K, H, W)
        per_kp = nd.mean(diff, axis=(2, 3))      # (B, K)
        vis = visible.astype("float32")
        n = nd.maximum(nd.sum(vis),
                       nd.ones((1,), ctx=pred.context))
        return nd.sum(per_kp * vis) / n


class PCKMetric(EvalMetric):
    """Percentage of Correct Keypoints at a distance threshold (the
    standard pose metric; GluonCV evaluates PCK/OKS families)."""

    def __init__(self, threshold=0.1):
        self.threshold = float(threshold)
        super().__init__(name=f"PCK@{threshold}")

    def update(self, labels, preds):
        if not isinstance(labels, (list, tuple)):
            labels, preds = [labels], [preds]
        for kp, pred in zip(labels, preds):
            kp = np.asarray(kp.asnumpy()
                            if hasattr(kp, "asnumpy") else kp, "f4")
            pred = np.asarray(pred.asnumpy()
                              if hasattr(pred, "asnumpy") else pred,
                              "f4")
            vis = kp[:, :, 2] > 0
            dist = np.sqrt(((pred - kp[:, :, :2]) ** 2).sum(-1))
            self._inc(float((dist[vis] < self.threshold).sum()),
                      int(vis.sum()))


def simple_pose_tiny(num_keypoints=4):
    """Test-size SimplePose over thumbnail resnet18."""
    from ..gluon.model_zoo import vision
    return SimplePose(num_keypoints,
                      vision.resnet18_v1(classes=10, thumbnail=True),
                      deconv_channels=32, num_deconv=2)
