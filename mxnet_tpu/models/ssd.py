"""SSD single-shot detector (capability parity: reference
``example/ssd/`` + GluonCV's SSD family over the contrib MultiBox ops —
SURVEY.md §2.2 detection row, §2.6 external zoos).

TPU-first design: everything is static-shape — anchors are a compile
time constant per input size, matching/NMS are fixed-trip (see
``ops/det.py``) — so the whole forward (and the training loss) lives in
one XLA program under ``hybridize()``.
"""
from __future__ import annotations

import numpy as np

from ..gluon.block import HybridBlock
from ..gluon import nn

__all__ = ["SSD", "ssd_tiny", "MultiBoxLoss"]


def _feature_block(channels, prefix):
    """conv-BN-relu ×2 then stride-2 downsample."""
    out = nn.HybridSequential(prefix=prefix)
    with out.name_scope():
        for _ in range(2):
            out.add(nn.Conv2D(channels, 3, padding=1, use_bias=False),
                    nn.BatchNorm(), nn.Activation("relu"))
        out.add(nn.MaxPool2D(2))
    return out


class SSD(HybridBlock):
    """Multi-scale SSD head over a small conv backbone.

    Per scale: a class predictor ``(A*(num_classes+1))``-channel conv
    and a box predictor ``(A*4)``-channel conv; anchors from
    ``_contrib_MultiBoxPrior``.  ``forward`` returns
    (anchors (1, N, 4), cls_preds (B, C+1, N), loc_preds (B, N*4)) —
    the exact triple MultiBoxTarget/MultiBoxDetection consume.
    """

    def __init__(self, num_classes, num_scales=3, base_channels=16,
                 sizes=None, ratios=None, **kwargs):
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self._num_scales = num_scales
        if sizes is None:
            lo, hi = 0.2, 0.9
            step = (hi - lo) / max(num_scales - 1, 1)
            sizes = [(lo + i * step,
                      lo + (i + 0.5) * step) for i in range(num_scales)]
        if ratios is None:
            ratios = [(1.0, 2.0, 0.5)] * num_scales
        self._sizes = [tuple(s) for s in sizes]
        self._ratios = [tuple(r) for r in ratios]
        with self.name_scope():
            self.features = []
            self.cls_heads = []
            self.box_heads = []
            for i in range(num_scales):
                feat = _feature_block(base_channels * (2 ** i),
                                      prefix=f"feat{i}_")
                a = len(self._sizes[i]) + len(self._ratios[i]) - 1
                cls = nn.Conv2D(a * (num_classes + 1), 3, padding=1,
                                prefix=f"cls{i}_")
                box = nn.Conv2D(a * 4, 3, padding=1, prefix=f"box{i}_")
                self.register_child(feat, f"feat{i}")
                self.register_child(cls, f"cls{i}")
                self.register_child(box, f"box{i}")
                self.features.append(feat)
                self.cls_heads.append(cls)
                self.box_heads.append(box)

    def hybrid_forward(self, F, x):
        anchors, cls_preds, loc_preds = [], [], []
        for i in range(self._num_scales):
            x = self.features[i](x)
            anchors.append(F._contrib_MultiBoxPrior(
                x, sizes=self._sizes[i], ratios=self._ratios[i]))
            c = self.cls_heads[i](x)       # (B, A*(C+1), H, W)
            b, _, h, w = c.shape
            # flatten PIXEL-major (slot n = pixel n//A, anchor n%A) to
            # line up with MultiBoxPrior's anchor order and loc_preds
            c = c.reshape((b, -1, self.num_classes + 1, h * w))
            c = c.transpose((0, 2, 3, 1)).reshape(
                (b, self.num_classes + 1, -1))
            cls_preds.append(c)
            l = self.box_heads[i](x).reshape((b, -1, 4, h * w))
            l = l.transpose((0, 3, 1, 2)).reshape((b, -1))
            loc_preds.append(l)
        anchors_all = F.concat(*anchors, dim=1)
        cls_all = F.concat(*cls_preds, dim=2)
        loc_all = F.concat(*loc_preds, dim=1)
        return anchors_all, cls_all, loc_all


class MultiBoxLoss:
    """SSD training loss: softmax CE on classes + smooth-L1 on offsets
    (reference example/ssd/train's loss pairing)."""

    def __call__(self, cls_preds, cls_target, loc_preds, loc_target,
                 loc_mask):
        from .. import ndarray as nd
        logp = nd.log_softmax(cls_preds, axis=1)           # (B, C+1, N)
        picked = nd.pick(logp.transpose((0, 2, 1)), cls_target, axis=2)
        ignore = cls_target >= 0
        # normalizer stays on device: no host sync inside the step
        n_kept = nd.maximum(nd.sum(ignore), nd.ones((1,)))
        cls_loss = -nd.sum(picked * ignore) / n_kept
        diff = (loc_preds - loc_target) * loc_mask
        adiff = nd.abs(diff)
        sl1 = nd.where(adiff > 1.0, adiff - 0.5, 0.5 * diff * diff)
        denom = nd.maximum(nd.sum(loc_mask), nd.ones((1,)))
        loc_loss = nd.sum(sl1) / denom
        return cls_loss + loc_loss


def ssd_tiny(num_classes=2, **kwargs):
    """Small SSD for tests/examples (3 scales, 16-ch base)."""
    return SSD(num_classes, num_scales=3, base_channels=16, **kwargs)
