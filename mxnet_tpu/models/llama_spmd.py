"""SPMD Llama: sharded checkpoint -> tp×pp mesh -> pipelined fine-tune.

The seam-composition layer VERDICT r4 #3 asked for: the pieces existed
separately (``hf_loader`` sharded index, ``parallel.planning`` tp×pp
layout, ``chunked_softmax_ce``, the 1F1B pipeline) — this module makes
them one story:

  * :func:`load_llama_stacked` reads an HF-layout (possibly sharded)
    safetensors checkpoint STRAIGHT onto a ``(tp, pp)`` device mesh via
    ``jax.make_array_from_callback``: each device's addressable shard is
    read from the zero-copy mmap view of exactly the bytes it owns —
    the full model is never materialized on the host (the multi-host
    contract; on a single host the page cache sees every byte but no
    full-tensor ndarray is ever built).  Layer weights come back
    STACKED over a leading stage axis sharded over ``pp`` (the jax
    pipeline layout), Megatron column/row-sharded over ``tp`` per
    ``parallel.planning.llama_param_rule``'s taxonomy.
  * :func:`make_stage_fn` is the functional decoder layer (RMSNorm →
    GQA attention with adjacent-pair RoPE → SwiGLU) that runs INSIDE
    ``parallel.pipeline_apply`` / ``pipeline_value_and_grad`` with
    ``lax.psum`` over ``tp`` closing the row-parallel projections —
    numerically identical to the Gluon ``_LlamaLayer`` (the parity
    test drives both from one checkpoint).
  * :func:`train_step` runs one fused 1F1B fine-tune step whose loss
    is ``chunked_softmax_ce`` — the (N, V) logits are never
    materialized even under pipeline + tensor parallelism.
  * :func:`save_llama_stacked` reshards the trained params back to an
    HF sharded checkpoint (inverse RoPE permutation included) that
    ``load_hf_llama`` / HF tooling can read.

Reference analog: upstream's closest is the manual model-parallel
example (SURVEY.md §2.3 "Model/tensor parallel") — checkpoint-to-mesh
streaming has no reference ancestor; designed TPU-first.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from .hf_loader import (_permute_qk, _rope_perm, _shard_paths,
                        read_safetensors, write_safetensors_sharded)

__all__ = ["load_llama_stacked", "make_stage_fn", "make_chunked_loss",
           "forward_logits", "train_step", "save_llama_stacked"]

# layer-param short name -> (HF suffix, sharding kind)
# kinds: col = output-dim tp shard, row = input-dim tp shard,
# norm = replicated gamma
_LAYER_TABLE = {
    "q": ("self_attn.q_proj.weight", "col"),
    "k": ("self_attn.k_proj.weight", "col"),
    "v": ("self_attn.v_proj.weight", "col"),
    "o": ("self_attn.o_proj.weight", "row"),
    "gate": ("mlp.gate_proj.weight", "col"),
    "up": ("mlp.up_proj.weight", "col"),
    "down": ("mlp.down_proj.weight", "row"),
    "innorm": ("input_layernorm.weight", "norm"),
    "postnorm": ("post_attention_layernorm.weight", "norm"),
}


def _open_views(path):
    """Every tensor in the (possibly sharded) checkpoint as a lazy
    mmap view; nothing is copied until a shard callback slices."""
    views = {}
    for shard in _shard_paths(path):
        views.update(read_safetensors(shard))
    return views


def _stacked_specs(tp_axis, pp_axis):
    """Leaf layout is (pp_stages, layers_per_stage, *tensor_dims):
    the stage dim shards over pp (the pipeline contract), the
    within-stage layer dim stays local, tp shards the Megatron dim."""
    from jax.sharding import PartitionSpec as P
    out = {}
    for name, (_, kind) in _LAYER_TABLE.items():
        if kind == "col":
            out[name] = P(pp_axis, None, tp_axis, None)
        elif kind == "row":
            out[name] = P(pp_axis, None, None, tp_axis)
        else:
            out[name] = P(pp_axis, None, None)
    return out


def load_llama_stacked(path, mesh, num_heads, num_kv_heads,
                       rope_base=10000.0, *, tp_axis="tp",
                       pp_axis="pp", dtype=np.float32):
    """Stream an HF Llama checkpoint onto a ``(tp, pp)`` mesh.

    Returns ``(params, specs, config)``:

    * ``params["layers"]`` — dict of STACKED
      ``(pp_stages, layers_per_stage, ...)`` jax arrays: the stage axis
      is sharded over ``pp_axis``, the within-stage layer axis is local,
      and Megatron col/row sharding rides ``tp_axis``; each device shard
      is built by ``jax.make_array_from_callback`` reading ONLY its own
      byte range from the checkpoint mmap (q/k rows pass through the
      rotate-half → adjacent-pair RoPE permutation lazily, per shard).
      Global layer id = stage * layers_per_stage + local index.
    * ``params["embed"]``, ``params["final_norm"]``, ``params["head"]``
      — replicated (``head`` is None for tied checkpoints; use the
      embedding).
    * ``specs`` — the PartitionSpec pytree for ``params["layers"]``
      (feed to ``pipeline_value_and_grad(param_specs=...)``).
    * ``config`` — dict(num_layers, layers_per_stage, units, hidden,
      vocab, head_dim, num_heads, num_kv_heads, rope_base) inferred
      from shapes.

    Requires ``mesh.shape[pp_axis]`` to DIVIDE ``num_layers`` (each
    stage runs ``num_layers / pp`` consecutive decoder layers — the
    homogeneous-stage pipeline contract) and ``tp | num_kv_heads``.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    views = _open_views(path)
    if "model.embed_tokens.weight" not in views:
        raise MXNetError(f"{path}: not a Llama checkpoint "
                         "(model.embed_tokens.weight missing)")
    vocab, units = views["model.embed_tokens.weight"].shape
    n_layers = 0
    while f"model.layers.{n_layers}.self_attn.q_proj.weight" in views:
        n_layers += 1
    if not n_layers:
        raise MXNetError(f"{path}: no decoder layers found")
    hidden = views["model.layers.0.mlp.gate_proj.weight"].shape[0]
    d = units // num_heads
    kv_rows = views["model.layers.0.self_attn.k_proj.weight"].shape[0]
    if kv_rows != num_kv_heads * d:
        raise MXNetError(
            f"k_proj rows {kv_rows} != num_kv_heads*head_dim "
            f"{num_kv_heads}*{d} — wrong num_heads/num_kv_heads?")
    tp = mesh.shape[tp_axis]
    pp = mesh.shape[pp_axis]
    if n_layers % pp:
        raise MXNetError(
            f"num_layers={n_layers} not divisible by mesh "
            f"{pp_axis}={pp} (stages must hold equal layer blocks)")
    lpp = n_layers // pp
    for what, val in (("num_heads", num_heads),
                      ("num_kv_heads", num_kv_heads),
                      ("hidden", hidden)):
        if val % tp:
            raise MXNetError(f"{what}={val} not divisible by "
                             f"{tp_axis}={tp}")

    # full-tensor row permutations for the RoPE layout change; slicing
    # perm[rows] keeps the per-shard read lazy
    perms = {"q": np.concatenate(
        [h * d + _rope_perm(d) for h in range(num_heads)]),
        "k": np.concatenate(
        [h * d + _rope_perm(d) for h in range(num_kv_heads)])}

    specs = _stacked_specs(tp_axis, pp_axis)
    layers = {}
    for name, (suffix, kind) in _LAYER_TABLE.items():
        per_layer = [views[f"model.layers.{i}.{suffix}"]
                     for i in range(n_layers)]
        # (pp, layers_per_stage, *tensor): global layer id is
        # stage * lpp + j — stage blocks are contiguous layer runs,
        # the GPipe assignment parallel.planning._layer_stage uses
        shape = (pp, lpp) + per_layer[0].shape
        sharding = NamedSharding(mesh, specs[name])
        perm = perms.get(name)

        def cb(index, per_layer=per_layer, perm=perm):
            ss, js = index[0], index[1]
            rest = index[2:]
            stages = []
            for stg in range(ss.start or 0,
                             ss.stop if ss.stop is not None else pp):
                slabs = []
                for j in range(js.start or 0,
                               js.stop if js.stop is not None
                               else lpp):
                    v = per_layer[stg * lpp + j]
                    if perm is not None:
                        rows = perm[rest[0]]
                        slab = v[rows]
                        if len(rest) > 1:
                            slab = slab[(slice(None),)
                                        + tuple(rest[1:])]
                    else:
                        slab = v[tuple(rest)]
                    slabs.append(np.asarray(slab, dtype))
                stages.append(np.stack(slabs))
            return np.stack(stages)

        layers[name] = jax.make_array_from_callback(shape, sharding,
                                                    cb)

    repl = NamedSharding(mesh, P())
    embed = jax.device_put(
        np.asarray(views["model.embed_tokens.weight"], dtype), repl)
    final_norm = jax.device_put(
        np.asarray(views["model.norm.weight"], dtype), repl)
    head = None
    if "lm_head.weight" in views:
        head = jax.device_put(
            np.asarray(views["lm_head.weight"], dtype), repl)
    params = {"layers": layers, "embed": embed,
              "final_norm": final_norm, "head": head}
    config = dict(num_layers=n_layers, layers_per_stage=lpp,
                  units=units, hidden=hidden,
                  vocab=vocab, head_dim=d, num_heads=num_heads,
                  num_kv_heads=num_kv_heads, rope_base=rope_base)
    return params, specs, config


def _rms(x, gamma, eps):
    import jax.numpy as jnp
    from jax import lax
    ms = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    return x * lax.rsqrt(ms + eps) * gamma


def make_stage_fn(config, tp_axis="tp", eps=1e-5):
    """Functional decoder STAGE for the pipeline: a block of
    ``layers_per_stage`` decoder layers, each matching the Gluon
    ``_LlamaLayer`` math exactly (RMSNorm eps 1e-5, adjacent-pair
    RoPE, GQA SDPA, SwiGLU), with Megatron tp: q/k/v/gate/up consume
    their column shard locally (heads split over tp — GQA groups stay
    aligned because ``tp | num_kv_heads``), o/down row-parallel
    partials closed by ONE ``lax.psum`` each.  Stage leaves arrive as
    ``(layers_per_stage, ...)`` local blocks (the pipeline strips the
    pp-sharded stage dim); the layer loop is unrolled — XLA sees a
    static chain, the TPU-friendly form."""
    h, kv, d = (config["num_heads"], config["num_kv_heads"],
                config["head_dim"])
    base = config["rope_base"]

    # NB: the returned closure must capture only scalars and
    # module-level functions — pipeline._capture_key keys opaque
    # objects by id, so a per-call inner function would defeat the
    # pipeline executable cache and recompile every step.
    def stage(local, x):
        # layers_per_stage derived from the leaves themselves: a
        # config/array mismatch is then impossible
        lpp = next(iter(local.values())).shape[0]
        for j in range(lpp):
            x = _decoder_layer({k: v[j] for k, v in local.items()},
                               x, h, kv, d, base, eps, tp_axis)
        return x

    return stage


def _decoder_layer(lp, x, h, kv, d, base, eps, tp_axis):
    """One decoder layer on its local tp shards (module-level so the
    pipeline executable cache keys it stably)."""
    import jax.numpy as jnp
    from jax import lax

    from ..ops.attention import dot_product_attention, rope

    from ..parallel._compat import axis_size
    tp = axis_size(tp_axis) if tp_axis else 1
    b, s = x.shape[0], x.shape[1]
    hl, kvl = h // tp, kv // tp
    hx = _rms(x, lp["innorm"], eps)
    q = rope(jnp.dot(hx, lp["q"].T).reshape(b, s, hl, d), base=base)
    k = rope(jnp.dot(hx, lp["k"].T).reshape(b, s, kvl, d), base=base)
    v = jnp.dot(hx, lp["v"].T).reshape(b, s, kvl, d)
    att = dot_product_attention(q, k, v, causal=True)
    o_part = jnp.dot(att.reshape(b, s, hl * d), lp["o"].T)
    if tp_axis:
        o_part = lax.psum(o_part, tp_axis)
    x = x + o_part
    hx = _rms(x, lp["postnorm"], eps)
    gate = jnp.dot(hx, lp["gate"].T)
    up = jnp.dot(hx, lp["up"].T)
    dn = jnp.dot(_silu(gate) * up, lp["down"].T)
    if tp_axis:
        dn = lax.psum(dn, tp_axis)
    return x + dn


def _silu(x):
    import jax
    return jax.nn.silu(x)


def make_chunked_loss(params, config, tp_axis="tp", vocab_chunk=None,
                      eps=1e-5):
    """Pipeline ``loss_fn``: final RMSNorm + streaming large-vocab CE
    over next-token labels — the (N, V) logits tensor is never
    materialized (``chunked_softmax_ce``'s scan), composing with both
    pipeline and tensor parallelism.  Head/embedding stay frozen (the
    embeddings-frozen fine-tune mode); returns the microbatch-mean
    loss, ``lax.pmean``-ed over ``tp`` (replicated activations make it
    identical per shard — the pmean keeps shard_map's varying-axes
    accounting exact)."""
    import jax.numpy as jnp
    from jax import lax

    from ..ops.nn import chunked_softmax_ce

    head_w = params["head"] if params["head"] is not None \
        else params["embed"]
    gamma = params["final_norm"]
    chunk = int(vocab_chunk or max(64, config["vocab"] // 4))
    u = config["units"]

    def loss_fn(out_mb, y_mb):
        hid = _rms(out_mb, gamma, eps)
        pred = hid[:, :-1].reshape(-1, u)
        labels = y_mb[:, 1:].reshape(-1).astype(jnp.int32)
        per_row = chunked_softmax_ce(pred, head_w, labels, chunk=chunk)
        loss = per_row.mean()
        if tp_axis:
            loss = lax.pmean(loss, tp_axis)
        return loss

    return loss_fn


def forward_logits(params, tokens, config, mesh, specs, *,
                   tp_axis="tp", pp_axis="pp", n_microbatches=None,
                   eps=1e-5):
    """Full forward to logits through the GPipe pipeline (parity /
    eval path; training uses :func:`train_step`)."""
    import jax.numpy as jnp

    from ..parallel.pipeline import pipeline_apply

    m = n_microbatches or mesh.shape[pp_axis]
    x = jnp.asarray(params["embed"])[jnp.asarray(tokens, jnp.int32)]
    stage = make_stage_fn(config, tp_axis=tp_axis, eps=eps)
    hid = pipeline_apply(stage, params["layers"], x, m, mesh=mesh,
                         axis=pp_axis, param_specs=specs)
    hid = _rms(hid, params["final_norm"], eps)
    head_w = params["head"] if params["head"] is not None \
        else params["embed"]
    return jnp.dot(hid, jnp.asarray(head_w).T)


def train_step(params, tokens, config, mesh, specs, *, lr=1e-2,
               tp_axis="tp", pp_axis="pp", n_microbatches=None,
               vocab_chunk=None, eps=1e-5):
    """ONE fused 1F1B fine-tune step: embed (frozen) → pipelined
    decoder stack (trained, tp×pp sharded) → chunked CE (frozen head).
    Returns ``(loss, params)`` with layer params SGD-updated in their
    sharded stacked layout (update arithmetic preserves shardings)."""
    import jax

    from ..parallel.pipeline import pipeline_value_and_grad

    m = n_microbatches or mesh.shape[pp_axis]
    import jax.numpy as jnp
    x = jnp.asarray(params["embed"])[jnp.asarray(tokens, jnp.int32)]
    stage = make_stage_fn(config, tp_axis=tp_axis, eps=eps)
    loss_fn = make_chunked_loss(params, config, tp_axis=tp_axis,
                                vocab_chunk=vocab_chunk, eps=eps)
    # tp is closed by psums (row-parallel projections + chunked CE):
    # declare it so replicated leaves (norm weights) get true
    # replicated grads back, not per-device partials
    loss, grads = pipeline_value_and_grad(
        stage, params["layers"], x, jnp.asarray(tokens, jnp.int32),
        loss_fn, m, mesh=mesh, axis=pp_axis, param_specs=specs,
        grad_reduce_axes=(tp_axis,))
    new_layers = jax.tree_util.tree_map(
        lambda p, g: p - lr * g, params["layers"], grads)
    return loss, {**params, "layers": new_layers}


def save_llama_stacked(params, dir_path, config, max_shard_bytes,
                       dtype=np.float32, metadata=None):
    """Reshard the (possibly trained) stacked params back to an
    HF-layout sharded checkpoint readable by ``load_hf_llama`` and HF
    tooling (inverse RoPE row permutation applied to q/k).

    Uses :func:`write_safetensors_sharded`'s streaming form: each
    tensor is gathered from its device shards only while ITS shard
    file is being written and dropped right after — peak host memory
    is one shard file, not the model (the save-side mirror of
    :func:`load_llama_stacked`'s contract)."""
    h, kv, d = (config["num_heads"], config["num_kv_heads"],
                config["head_dim"])
    # layers_per_stage derived from the arrays (not config) so a
    # hand-built or stale config cannot silently mis-index layers
    lpp = next(iter(params["layers"].values())).shape[1]
    sources = {}                      # hf name -> (kind, array, layer)
    for name, (suffix, _) in _LAYER_TABLE.items():
        for i in range(config["num_layers"]):
            sources[f"model.layers.{i}.{suffix}"] = (
                name, params["layers"][name], i)
    sources["model.embed_tokens.weight"] = (None, params["embed"], None)
    sources["model.norm.weight"] = (None, params["final_norm"], None)
    if params["head"] is not None:
        sources["lm_head.weight"] = (None, params["head"], None)

    def shape_of(kind, arr, layer):
        return tuple(arr.shape[2:] if layer is not None else arr.shape)

    specs = {nm: (shape_of(*src), dtype)
             for nm, src in sources.items()}

    def materialize(nm):
        kind, arr, layer = sources[nm]
        # stacked layout is (stage, layer_in_stage, ...): global layer
        # i lives at [i // lpp, i % lpp]
        a = np.asarray(arr[layer // lpp, layer % lpp]
                       if layer is not None else arr, dtype)
        if kind == "q":
            a = _permute_qk(a, h, d, invert=True).astype(dtype)
        elif kind == "k":
            a = _permute_qk(a, kv, d, invert=True).astype(dtype)
        return a

    return write_safetensors_sharded(
        dir_path, specs, max_shard_bytes, materialize=materialize,
        metadata=metadata or {"format": "pt",
                              "producer": "mxnet_tpu.llama_spmd"})
