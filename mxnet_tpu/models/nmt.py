"""Transformer NMT + beam search (capability target: GluonNLP
``transformer_en_de_512`` and ``BeamSearchSampler`` — SURVEY.md §2.6
"External zoos", upstream example/gluon NMT scripts).

TPU-first design notes:
- The whole teacher-forcing step (encoder + decoder + label-smoothed
  loss) hybridizes to ONE XLA program; attention is the fused SDPA op
  (flash kernel on chip).
- Incremental translation mirrors ``LlamaForCausalLM``: per-layer
  self-attention KV caches written in place at a dynamic offset, so
  every decode step reuses one compiled program regardless of position.
  Cross-attention K/V are projected from the encoder memory ONCE at
  decode init — the classic inference-time transformer optimization.
- ``BeamSearchSampler`` keeps all heavy math on device: candidate
  scores and the (K·V)-wide top-k run as device programs; only the
  (B, K) winner bookkeeping happens on host.  Beam-reordering of the
  cached decoder state is a batched ``take`` along axis 0.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..gluon.block import HybridBlock
from ..gluon import nn
from ..gluon.contrib.nn import TransformerEncoder

__all__ = ["TransformerNMT", "BeamSearchScorer", "BeamSearchSampler",
           "get_nmt", "nmt_tiny", "transformer_en_de_512"]


def _sinusoid_table(max_len, units):
    """Vaswani-style fixed position encodings (max_len, units)."""
    pos = np.arange(max_len, dtype=np.float64)[:, None]
    dim = np.arange(units // 2, dtype=np.float64)[None, :]
    ang = pos / np.power(10000.0, 2.0 * dim / units)
    table = np.zeros((max_len, units), dtype=np.float32)
    table[:, 0::2] = np.sin(ang)
    table[:, 1::2] = np.cos(ang)
    return table


class _DecoderAttention(HybridBlock):
    """Self- or cross-attention with explicit projections so the decode
    path can cache K/V (MultiHeadAttention hides its projections and has
    no incremental step)."""

    def __init__(self, units, num_heads, **kwargs):
        super().__init__(**kwargs)
        if units % num_heads:
            raise MXNetError(f"units {units} % num_heads {num_heads}")
        self._h = num_heads
        self._d = units // num_heads
        self._units = units
        with self.name_scope():
            self.q_proj = nn.Dense(units, flatten=False, in_units=units,
                                   prefix="q_")
            self.k_proj = nn.Dense(units, flatten=False, in_units=units,
                                   prefix="k_")
            self.v_proj = nn.Dense(units, flatten=False, in_units=units,
                                   prefix="v_")
            self.o_proj = nn.Dense(units, flatten=False, in_units=units,
                                   prefix="o_")

    def _split(self, F, x):
        b, s = x.shape[0], x.shape[1]
        return x.reshape((b, s, self._h, self._d))

    def hybrid_forward(self, F, query, key, value, mask=None,
                       causal=False):
        b, s_q = query.shape[0], query.shape[1]
        q = self._split(F, self.q_proj(query))
        k = self._split(F, self.k_proj(key))
        v = self._split(F, self.v_proj(value))
        if mask is not None:
            out = F.dot_product_attention(q, k, v, mask, causal=causal,
                                          use_mask=True)
        else:
            out = F.dot_product_attention(q, k, v, causal=causal)
        return self.o_proj(out.reshape((b, s_q, self._units)))

    def project_kv(self, memory):
        """Encoder memory → (K, V) in (B, S, H, D), computed once per
        translation instead of once per step."""
        k = self._split(None, self.k_proj(memory))
        v = self._split(None, self.v_proj(memory))
        return k, v

    def step_self(self, x, cache_k, cache_v, offset, mask):
        """One-token self-attention against the in-place KV cache."""
        from .. import ndarray as nd
        b = x.shape[0]
        q = self._split(None, self.q_proj(x))
        k_t = self._split(None, self.k_proj(x))
        v_t = self._split(None, self.v_proj(x))
        nd._cache_update(cache_k, k_t, offset=offset, out=cache_k)
        nd._cache_update(cache_v, v_t, offset=offset, out=cache_v)
        out = nd.dot_product_attention(q, cache_k, cache_v, mask,
                                       use_mask=True)
        return self.o_proj(out.reshape((b, 1, self._units)))

    def step_cross(self, x, mem_k, mem_v, mask=None):
        """One-token cross-attention against pre-projected memory."""
        from .. import ndarray as nd
        b = x.shape[0]
        q = self._split(None, self.q_proj(x))
        if mask is not None:
            out = nd.dot_product_attention(q, mem_k, mem_v, mask,
                                           use_mask=True)
        else:
            out = nd.dot_product_attention(q, mem_k, mem_v)
        return self.o_proj(out.reshape((b, 1, self._units)))


class TransformerDecoderCell(HybridBlock):
    """Post-LN decoder layer: self-attn → cross-attn → FFN, residual
    around each (Vaswani layout, as the reference transformer)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 activation="relu", **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.self_attn = _DecoderAttention(units, num_heads,
                                               prefix="self_")
            self.cross_attn = _DecoderAttention(units, num_heads,
                                                prefix="cross_")
            self.ffn_1 = nn.Dense(hidden_size, flatten=False,
                                  in_units=units, prefix="ffn1_")
            self.ffn_2 = nn.Dense(units, flatten=False,
                                  in_units=hidden_size, prefix="ffn2_")
            self.norm_self = nn.LayerNorm(in_channels=units)
            self.norm_cross = nn.LayerNorm(in_channels=units)
            self.norm_ffn = nn.LayerNorm(in_channels=units)
            self.drop = nn.Dropout(dropout) if dropout else None
        self._activation = activation

    def _ffn(self, F, x):
        h = self.ffn_1(x)
        h = F.Activation(h, act_type=self._activation)
        h = self.ffn_2(h)
        if self.drop is not None:
            h = self.drop(h)
        return h

    def hybrid_forward(self, F, x, memory, tgt_mask=None,
                       mem_mask=None):
        att = self.self_attn(x, x, x, tgt_mask, True)
        if self.drop is not None:
            att = self.drop(att)
        x = self.norm_self(x + att)
        att = self.cross_attn(x, memory, memory, mem_mask, False)
        if self.drop is not None:
            att = self.drop(att)
        x = self.norm_cross(x + att)
        return self.norm_ffn(x + self._ffn(F, x))

    def step(self, x, cache_k, cache_v, offset, self_mask, mem_k,
             mem_v, mem_mask):
        from .. import ndarray as nd
        att = self.self_attn.step_self(x, cache_k, cache_v, offset,
                                       self_mask)
        x = self.norm_self(x + att)
        att = self.cross_attn.step_cross(x, mem_k, mem_v, mem_mask)
        x = self.norm_cross(x + att)
        return self.norm_ffn(x + self._ffn(nd, x))


class TransformerNMT(HybridBlock):
    """Encoder-decoder transformer for sequence-to-sequence tasks.

    Conventions (GluonNLP NMT): token 0 usable as PAD, the caller
    supplies BOS/EOS ids; ``hybrid_forward`` is the teacher-forcing
    pass returning (B, T, tgt_vocab) logits; ``translate`` runs beam
    search through the cached incremental decoder.
    """

    def __init__(self, src_vocab_size, tgt_vocab_size=None, units=512,
                 hidden_size=2048, num_layers=6, num_heads=8,
                 max_length=512, dropout=0.1, activation="relu",
                 share_embed=False, tie_output=True, **kwargs):
        super().__init__(**kwargs)
        if share_embed and tgt_vocab_size not in (None, src_vocab_size):
            raise MXNetError("share_embed requires equal vocabularies")
        tgt_vocab_size = tgt_vocab_size or src_vocab_size
        self.src_vocab_size = src_vocab_size
        self.tgt_vocab_size = tgt_vocab_size
        self._units = units
        self._scale = float(np.sqrt(units))
        self._tied = tie_output
        self._num_layers = num_layers
        self._heads = num_heads
        with self.name_scope():
            self.src_embed = nn.Embedding(src_vocab_size, units,
                                          prefix="src_embed_")
            self.tgt_embed = (self.src_embed if share_embed else
                              nn.Embedding(tgt_vocab_size, units,
                                           prefix="tgt_embed_"))
            self.pos_table = self.params.get_constant(
                "pos_table", _sinusoid_table(max_length, units))
            self.encoder = TransformerEncoder(
                units, hidden_size, num_layers, num_heads,
                dropout=dropout, activation=activation, prefix="enc_")
            self.decoder_cells = []
            for i in range(num_layers):
                cell = TransformerDecoderCell(
                    units, hidden_size, num_heads, dropout=dropout,
                    activation=activation, prefix=f"dec{i}_")
                self.register_child(cell)
                self.decoder_cells.append(cell)
            if not tie_output:
                self.out_proj = nn.Dense(tgt_vocab_size, flatten=False,
                                         use_bias=False, in_units=units,
                                         prefix="out_")

    # ---- masks -------------------------------------------------------

    @staticmethod
    def _key_mask(F, valid_length, s, ctx):
        """(B,) valid lengths → (B, 1, 1, S) boolean key mask."""
        steps = F.arange(0, s, ctx=ctx)
        m = F.broadcast_lesser(
            F.expand_dims(steps, axis=0),
            F.expand_dims(valid_length.astype("float32"), axis=1))
        return F.expand_dims(F.expand_dims(m, axis=1), axis=1)

    # ---- teacher-forcing path ---------------------------------------

    def _embed(self, F, embed, tokens, pos_table=None):
        s = tokens.shape[1]
        if pos_table is None:
            pos_table = self.pos_table.data(tokens.context)
        pos = F.slice_axis(pos_table, axis=0, begin=0, end=s)
        return embed(tokens) * self._scale + F.expand_dims(pos, axis=0)

    def _head(self, F, h):
        if self._tied:
            w = self.tgt_embed.weight.data(h.context)
            b, s, u = h.shape
            return F.dot(h.reshape((b * s, u)), w,
                         transpose_b=True).reshape(
                             (b, s, self.tgt_vocab_size))
        return self.out_proj(h)

    def encode(self, src, src_valid=None):
        from .. import ndarray as nd
        x = self._embed(nd, self.src_embed, src)
        mask = None
        if src_valid is not None:
            mask = self._key_mask(nd, src_valid, src.shape[1],
                                  src.context)
        return self.encoder(x, mask)

    def hybrid_forward(self, F, src, tgt, src_valid=None,
                       tgt_valid=None, pos_table=None):
        s_src, s_tgt = src.shape[1], tgt.shape[1]
        x = self._embed(F, self.src_embed, src, pos_table)
        src_mask = None
        if src_valid is not None:
            src_mask = self._key_mask(F, src_valid, s_src, src.context)
        memory = self.encoder(x, src_mask)

        y = self._embed(F, self.tgt_embed, tgt, pos_table)
        tgt_mask = None
        if tgt_valid is not None:
            tgt_mask = self._key_mask(F, tgt_valid, s_tgt, tgt.context)
        for cell in self.decoder_cells:
            y = cell(y, memory, tgt_mask, src_mask)
        return self._head(F, y)

    def loss(self, src, tgt_in, tgt_out, src_valid=None, tgt_valid=None,
             label_smoothing=0.1):
        """Label-smoothed cross entropy (Vaswani ε=0.1), masked to the
        valid target positions; returns a scalar."""
        from .. import ndarray as nd
        logits = self(src, tgt_in, src_valid, tgt_valid)
        b, t, v = logits.shape
        logp = nd.log_softmax(logits.reshape((b * t, v)), axis=-1)
        lbl = tgt_out.reshape((-1,)).astype("int32")
        nll = -nd.pick(logp, lbl, axis=-1)
        smooth = -nd.mean(logp, axis=-1)
        per_tok = ((1.0 - label_smoothing) * nll
                   + label_smoothing * smooth)
        if tgt_valid is not None:
            steps = nd.arange(0, t, ctx=src.context).reshape((1, t))
            keep = (steps < tgt_valid.astype("float32").reshape(
                (b, 1))).astype("float32").reshape((-1,))
            return nd.sum(per_tok * keep) / nd.sum(keep)
        return nd.mean(per_tok)

    # ---- incremental decode (beam/greedy) ---------------------------

    def init_decode(self, memory, max_len, src_valid=None):
        """Build decode state: per-layer empty self-attn caches
        (``states`` — the part beam search reorders), pre-projected
        cross K/V (``mem_kvs`` — invariant across steps, kept OUT of
        the reordered state so beams never re-gather it), and the
        memory key mask."""
        from .. import ndarray as nd
        if max_len > self.pos_table.shape[0]:
            raise MXNetError(
                f"max_len {max_len} exceeds the position table "
                f"({self.pos_table.shape[0]} rows; raise max_length)")
        b = memory.shape[0]
        h, d = self._heads, self._units // self._heads
        states, mem_kvs = [], []
        for cell in self.decoder_cells:
            ck = nd.zeros((b, max_len, h, d), ctx=memory.context)
            cv = nd.zeros((b, max_len, h, d), ctx=memory.context)
            states.append([ck, cv])
            mem_kvs.append(cell.cross_attn.project_kv(memory))
        mem_mask = None
        if src_valid is not None:
            mem_mask = self._key_mask(nd, src_valid, memory.shape[1],
                                      memory.context)
        return states, mem_kvs, mem_mask

    def decode_step(self, tok, states, mem_kvs, offset, mem_mask=None):
        """tok (B, 1) → log-probs (B, tgt_vocab); states updated in
        place.  One compiled program for every position: the position
        row is fetched with a dynamic ``take`` (a static slice at
        ``offset`` would bake the position into the program and compile
        anew each step)."""
        from .. import ndarray as nd
        pos_idx = nd.array(np.array([offset], np.float32),
                           ctx=tok.context)
        pos = nd.take(self.pos_table.data(tok.context), pos_idx, axis=0)
        x = (self.tgt_embed(tok) * self._scale
             + nd.expand_dims(pos, axis=0))
        max_len = states[0][0].shape[1]
        # mask on the token's device (no cpu backend under axon)
        self_mask = (nd.arange(max_len, ctx=tok.context)
                     <= float(offset)).reshape((1, 1, 1, max_len))
        for cell, (ck, cv), (mk, mv) in zip(self.decoder_cells, states,
                                            mem_kvs):
            x = cell.step(x, ck, cv, offset, self_mask, mk, mv,
                          mem_mask)
        logits = self._head(nd, x).reshape((x.shape[0],
                                            self.tgt_vocab_size))
        return nd.log_softmax(logits, axis=-1)

    def translate(self, src, bos_id, eos_id, src_valid=None,
                  beam_size=4, max_len=None, alpha=1.0):
        """Beam-search translation → (samples (B, K, L), scores (B, K),
        lengths (B, K)); samples start with BOS and include EOS when
        produced."""
        max_len = min(max_len or (2 * src.shape[1] + 8),
                      self.pos_table.shape[0])
        memory = self.encode(src, src_valid)
        sampler = BeamSearchSampler(
            beam_size=beam_size, eos_id=eos_id,
            scorer=BeamSearchScorer(alpha=alpha), max_length=max_len)

        from .. import ndarray as nd
        b = src.shape[0]
        mem_t = _tile_rows(memory, beam_size)
        sv_t = None
        if src_valid is not None:
            sv_t = _tile_rows(src_valid, beam_size)
        states, mem_kvs, mem_mask = self.init_decode(mem_t, max_len,
                                                     sv_t)

        def decoder(tok, step_idx, st):
            return (self.decode_step(tok, st, mem_kvs, step_idx,
                                     mem_mask), st)

        start = nd.full((b * beam_size, 1), float(bos_id),
                        ctx=src.context)
        return sampler(decoder, start, states, batch_size=b)


def _tile_rows(x, k):
    """(B, ...) → (B*K, ...) with each row repeated K times."""
    from .. import ndarray as nd
    return nd.repeat(x, repeats=k, axis=0)


class BeamSearchScorer:
    """Google-NMT length-penalized score (Wu et al. 2016), the
    GluonNLP default: score = logprob_sum / ((5 + len) / 6) ** alpha."""

    def __init__(self, alpha=1.0, K=5.0):
        self.alpha = float(alpha)
        self.K = float(K)

    def __call__(self, log_probs, length):
        lp = ((self.K + length) / (self.K + 1.0)) ** self.alpha
        return log_probs / lp


class BeamSearchSampler:
    """Generic beam search over an incremental decoder.

    ``decoder(tok, step_idx, states) -> (log_probs (B*K, V), states)``
    with states any nest of NDArrays whose leading axis is the flat
    beam axis B*K — after each step the sampler reorders that axis by
    the surviving beams' parent indices (a device ``take``).

    Device/host split: per-step score expansion and the (K·V)-wide
    top-k run on device; only the (B, 2K) winner indices come to host
    for the EOS/finished bookkeeping.
    """

    def __init__(self, beam_size, eos_id, scorer=None, max_length=64):
        self.beam_size = int(beam_size)
        self.eos_id = int(eos_id)
        self.scorer = scorer or BeamSearchScorer()
        self.max_length = int(max_length)

    def __call__(self, decoder, start_tokens, states, batch_size):
        from .. import ndarray as nd
        b, k = batch_size, self.beam_size
        if start_tokens.shape[0] != b * k:
            raise MXNetError(
                f"start_tokens leading axis {start_tokens.shape[0]} != "
                f"batch_size*beam_size {b * k}")
        ctx = start_tokens.context
        # beam 0 of each batch row is live; the rest start at -inf so
        # the first expansion seeds distinct hypotheses from beam 0
        logp_sum = np.full((b, k), -np.inf, np.float64)
        logp_sum[:, 0] = 0.0
        hist = start_tokens.asnumpy().astype(np.int64).reshape(b, k, 1)
        alive = np.ones((b, k), bool)
        lengths = np.ones((b, k), np.int64)   # counts BOS
        cur = start_tokens
        finished = [[] for _ in range(b)]     # (score, token_list)

        for step in range(self.max_length - 1):
            logp, states = decoder(cur, step, states)  # (B*K, V)
            v = logp.shape[-1]
            # dead/unfilled beams carry -inf sums; clamp to a finite
            # floor so the device-side add never produces NaN (the
            # -1e29 host filter below then discards their children —
            # -inf * 0 tricks would leave NaN, whose top_k order is
            # unspecified)
            cand = logp + nd.array(
                np.maximum(logp_sum, -1e30).reshape(-1, 1)
                .astype(np.float32), ctx=ctx)
            # (B, K*V) top-2K on device; 2K so EOS picks never starve
            # the live-beam quota
            cand = cand.reshape((b, k * v))
            n_top = min(2 * k, k * v)
            top_scores, top_idx = nd.topk(
                cand, k=n_top, axis=-1, ret_typ="both")
            ts = top_scores.asnumpy().astype(np.float64)
            ti = top_idx.asnumpy().astype(np.int64)

            new_logp = np.full((b, k), -np.inf, np.float64)
            new_alive = np.zeros((b, k), bool)
            new_len = np.ones((b, k), np.int64)
            parent = np.zeros((b, k), np.int64)
            next_tok = np.zeros((b, k), np.int64)
            for i in range(b):
                slot = 0
                for j in range(n_top):
                    if slot == k:
                        break
                    if ts[i, j] <= -1e29:
                        continue
                    pj, tj = divmod(int(ti[i, j]), v)
                    seq_len = lengths[i, pj] + 1
                    if tj == self.eos_id:
                        seq = np.concatenate(
                            [hist[i, pj], [self.eos_id]])
                        sc = self.scorer(ts[i, j], float(seq_len))
                        finished[i].append((sc, seq))
                        continue
                    new_logp[i, slot] = ts[i, j]
                    new_alive[i, slot] = True
                    new_len[i, slot] = seq_len
                    parent[i, slot] = pj
                    next_tok[i, slot] = tj
                    slot += 1
            logp_sum, alive, lengths = new_logp, new_alive, new_len
            if not alive.any():
                break
            # reorder the beam axis of every state by parent index
            flat_parent = (parent
                           + np.arange(b)[:, None] * k).reshape(-1)
            hist = np.concatenate(
                [hist[np.arange(b)[:, None], parent],
                 next_tok[:, :, None]], axis=-1)
            if step < self.max_length - 2:
                # the final iteration's gather/upload would never be
                # consumed — only the host-side close-out remains
                idx_nd = nd.array(flat_parent.astype(np.float32),
                                  ctx=ctx)
                states = _gather_states(states, idx_nd)
                cur = nd.array(next_tok.reshape(b * k, 1).astype(
                    np.float32), ctx=ctx)

        # close out still-alive beams without EOS at max length
        for i in range(b):
            for j in range(k):
                if alive[i, j]:
                    sc = self.scorer(logp_sum[i, j],
                                     float(lengths[i, j]))
                    finished[i].append((sc, hist[i, j]))
            if not finished[i]:   # degenerate: everything pruned
                finished[i].append((-np.inf, hist[i, 0]))

        # pad + sort per batch row, best first
        max_out = max(len(s) for row in finished for _, s in row)
        samples = np.full((b, k, max_out), self.eos_id, np.int64)
        scores = np.full((b, k), -np.inf, np.float64)
        lens = np.zeros((b, k), np.int64)
        for i in range(b):
            best = sorted(finished[i], key=lambda t: -t[0])[:k]
            for j, (sc, seq) in enumerate(best):
                samples[i, j, :len(seq)] = seq
                scores[i, j] = sc
                lens[i, j] = len(seq)
        return (nd.array(samples.astype(np.float32), ctx=ctx),
                nd.array(scores.astype(np.float32), ctx=ctx),
                nd.array(lens.astype(np.float32), ctx=ctx))


def _gather_states(states, idx_nd):
    """Reorder the leading (flat beam) axis of every NDArray in a nest."""
    from .. import ndarray as nd
    if hasattr(states, "context"):   # NDArray leaf
        return nd.take(states, idx_nd, axis=0)
    if isinstance(states, (list, tuple)):
        out = [_gather_states(s, idx_nd) for s in states]
        return out if isinstance(states, list) else tuple(out)
    return states


_NMT_SPECS = {
    # test-size config (trains in seconds on the CPU backend)
    "nmt_tiny": dict(units=32, hidden_size=64, num_layers=2,
                     num_heads=2, max_length=64, dropout=0.0),
    # the GluonNLP WMT en-de base config
    "transformer_en_de_512": dict(units=512, hidden_size=2048,
                                  num_layers=6, num_heads=8,
                                  max_length=512, dropout=0.1),
}


def get_nmt(name, src_vocab_size, tgt_vocab_size=None, **kwargs):
    if name not in _NMT_SPECS:
        raise MXNetError(f"unknown nmt config {name!r}; options "
                         f"{sorted(_NMT_SPECS)}")
    spec = dict(_NMT_SPECS[name])
    spec.update(kwargs)
    return TransformerNMT(src_vocab_size, tgt_vocab_size, **spec)


def nmt_tiny(src_vocab_size, **kwargs):
    return get_nmt("nmt_tiny", src_vocab_size, **kwargs)


def transformer_en_de_512(src_vocab_size, **kwargs):
    return get_nmt("transformer_en_de_512", src_vocab_size, **kwargs)
