"""Probabilistic time-series forecasters (capability target: GluonTS
DeepAR and Transformer — SURVEY.md §2.6 "External zoos"; BASELINE
config #4 "GluonTS DeepAR / Transformer forecasting — RNN scan
lowering").

TPU-first design notes:

* The DeepAR training pass is ONE hybridizable program: the whole
  teacher-forced unroll lowers through ``gluon.rnn.LSTM``'s
  ``lax.scan`` path (the "RNN scan lowering" milestone), so XLA sees a
  single fused graph — no per-step Python.
* The Transformer forecaster reuses the contrib attention blocks (fused
  SDPA path); its decoder does causal self-attention + cross-attention
  over the encoded context.
* Both emit a Gaussian likelihood head with GluonTS's mean-|x| scaling
  trick, train on negative log-likelihood, and sample autoregressively
  for prediction (eager loop: sampling is latency-, not
  throughput-bound).
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..gluon.block import HybridBlock
from ..gluon import nn, rnn
from ..gluon.contrib.nn import (MultiHeadAttention, PositionwiseFFN,
                                TransformerEncoder)

__all__ = ["DeepAR", "TransformerForecaster", "gaussian_nll"]

_MIN_SIGMA = 1e-4


def gaussian_nll(F, target, mu, sigma):
    """Per-element Gaussian negative log-likelihood."""
    return (F.log(sigma)
            + 0.5 * float(np.log(2 * np.pi))
            + 0.5 * F.square((target - mu) / sigma))


class _GaussianHead(HybridBlock):
    """Projects features → (mu, sigma); sigma via softplus."""

    def __init__(self, in_units, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.mu_proj = nn.Dense(1, flatten=False, in_units=in_units,
                                    prefix="mu_")
            self.sigma_proj = nn.Dense(1, flatten=False,
                                       in_units=in_units,
                                       prefix="sigma_")

    def hybrid_forward(self, F, h):
        mu = self.mu_proj(h).reshape(h.shape[:-1])
        raw = self.sigma_proj(h).reshape(h.shape[:-1])
        sigma = F.Activation(raw, act_type="softrelu") + _MIN_SIGMA
        return mu, sigma


def _mean_abs_scale(F, context):
    """GluonTS mean-|x| scale over the time axis, (B,) → (B, 1)."""
    return F.mean(F.abs(context), axis=1, keepdims=True) + 1.0


class DeepAR(HybridBlock):
    """Autoregressive LSTM forecaster (capability parity: GluonTS
    ``DeepAREstimator``'s train network).

    Training call: ``loss = net(past_target, future_target)`` —
    teacher-forced unroll over context+prediction range, returns per-
    sample NLL ``(B,)``.  The unroll is a single ``lax.scan`` under
    hybridize/jit.

    Prediction: :meth:`sample` draws ancestral sample paths;
    :meth:`forecast` returns the deterministic mean path.
    """

    def __init__(self, context_length, prediction_length, num_cells=40,
                 num_layers=2, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        if context_length < 1 or prediction_length < 1:
            raise MXNetError("context_length and prediction_length must "
                             "be >= 1")
        self.context_length = int(context_length)
        self.prediction_length = int(prediction_length)
        self._num_cells = int(num_cells)
        with self.name_scope():
            self.lstm = rnn.LSTM(num_cells, num_layers=num_layers,
                                 layout="NTC", dropout=dropout,
                                 input_size=1, prefix="lstm_")
            self.head = _GaussianHead(num_cells, prefix="head_")

    def hybrid_forward(self, F, past_target, future_target):
        """Teacher-forced NLL over the full unrolled range, (B,)."""
        scale = _mean_abs_scale(F, past_target)          # (B, 1)
        full = F.concat(past_target, future_target, dim=1) / scale
        inputs = F.expand_dims(
            F.slice_axis(full, axis=1, begin=0, end=-1), axis=2)
        labels = F.slice_axis(full, axis=1, begin=1, end=None)
        h = self.lstm(inputs)                            # (B, T-1, H)
        mu, sigma = self.head(h)
        nll = gaussian_nll(F, labels, mu, sigma)
        # sigma is in scaled space: + log(scale) restores the true
        # likelihood's normalization (constant wrt params per sample)
        return F.mean(nll, axis=1) + F.mean(F.log(scale), axis=1)

    # -- prediction (eager) ----------------------------------------------
    def _warm_up(self, past_target):
        """Advance the LSTM over past[:-1]; past[-1] stays unconsumed as
        the first prediction step's input — matching the training
        alignment (step t's input is target[t-1], label target[t])."""
        from .. import ndarray as nd
        scale = _mean_abs_scale(nd, past_target)
        past_scaled = past_target / scale
        states = self.lstm.begin_state(past_target.shape[0],
                                       ctx=past_target.context)
        if past_target.shape[1] > 1:
            ctx_in = nd.expand_dims(
                nd.slice_axis(past_scaled, axis=1, begin=0, end=-1),
                axis=2)
            h, states = self.lstm(ctx_in, states)
        else:
            h = None
        last = nd.slice_axis(past_scaled, axis=1, begin=-1, end=None)
        return h, states, scale, last

    def forecast(self, past_target):
        """Deterministic mean path, (B, prediction_length)."""
        from .. import ndarray as nd
        h, states, scale, prev = self._warm_up(past_target)
        outs = []
        for _ in range(self.prediction_length):
            step_in = nd.expand_dims(prev, axis=2)
            h, states = self.lstm(step_in, states)
            mu, _ = self.head(h)
            prev = mu.reshape((-1, 1))
            outs.append(prev * scale)
        return nd.concat(*outs, dim=1)

    def sample(self, past_target, num_samples=100):
        """Ancestral sample paths, (num_samples, B, prediction_length)."""
        from .. import ndarray as nd
        from .. import random as mxrand
        b = past_target.shape[0]
        rep = nd.repeat(past_target, repeats=num_samples, axis=0)
        h, states, scale, prev = self._warm_up(rep)
        outs = []
        for _ in range(self.prediction_length):
            step_in = nd.expand_dims(prev, axis=2)
            h, states = self.lstm(step_in, states)
            mu, sigma = self.head(h)
            eps = mxrand.normal(0, 1, shape=mu.shape,
                                ctx=past_target.context)
            z = (mu + sigma * eps).reshape((-1, 1))
            prev = z
            outs.append(z * scale)
        paths = nd.concat(*outs, dim=1)      # (B*S, P)
        return paths.reshape((b, num_samples,
                              self.prediction_length)).transpose(
                                  (1, 0, 2))


class _TransformerDecoderCell(HybridBlock):
    """Causal self-attention + cross-attention + FFN (post-LN)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.self_att = MultiHeadAttention(units, num_heads,
                                               dropout=dropout)
            self.cross_att = MultiHeadAttention(units, num_heads,
                                                dropout=dropout)
            self.ffn = PositionwiseFFN(units, hidden_size,
                                       dropout=dropout)
            self.norm_self = nn.LayerNorm(in_channels=units)
            self.norm_cross = nn.LayerNorm(in_channels=units)
            self.norm_ffn = nn.LayerNorm(in_channels=units)

    def hybrid_forward(self, F, x, memory, causal_mask):
        x = self.norm_self(x + self.self_att(x, None, None, causal_mask))
        x = self.norm_cross(x + self.cross_att(x, memory, memory))
        return self.norm_ffn(x + self.ffn(x))


class TransformerForecaster(HybridBlock):
    """Encoder-decoder attention forecaster (capability parity: GluonTS
    ``TransformerEstimator``).

    Training call: ``loss = net(past_target, future_target)`` → (B,)
    NLL.  Encoder attends over the scaled context; the decoder runs
    causal self-attention over the teacher-forced target prefix plus
    cross-attention into the encoder memory; Gaussian head + NLL.
    """

    def __init__(self, context_length, prediction_length, units=32,
                 hidden_size=64, num_heads=4, enc_layers=2, dec_layers=2,
                 dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        if context_length < 1 or prediction_length < 1:
            raise MXNetError("context_length and prediction_length must "
                             "be >= 1")
        self.context_length = int(context_length)
        self.prediction_length = int(prediction_length)
        self._units = units
        with self.name_scope():
            self.enc_proj = nn.Dense(units, flatten=False, in_units=1,
                                     prefix="encproj_")
            self.dec_proj = nn.Dense(units, flatten=False, in_units=1,
                                     prefix="decproj_")
            self.enc_pos = self.params.get(
                "enc_pos", shape=(context_length, units), init="normal")
            self.dec_pos = self.params.get(
                "dec_pos", shape=(prediction_length, units),
                init="normal")
            self.encoder = TransformerEncoder(
                units, hidden_size, enc_layers, num_heads,
                dropout=dropout, prefix="enc_")
            self.dec_cells = []
            for i in range(dec_layers):
                cell = _TransformerDecoderCell(
                    units, hidden_size, num_heads, dropout=dropout,
                    prefix=f"dec{i}_")
                self.register_child(cell, f"dec{i}")
                self.dec_cells.append(cell)
            self.head = _GaussianHead(units, prefix="head_")

    def _causal_mask(self, F, length, ctx):
        steps = F.arange(0, length, ctx=ctx)
        m = F.broadcast_greater_equal(F.expand_dims(steps, axis=1),
                                      F.expand_dims(steps, axis=0))
        return m.reshape((1, 1, length, length))

    def _encode(self, F, past_scaled, enc_pos):
        x = self.enc_proj(F.expand_dims(past_scaled, axis=2))
        x = x + F.expand_dims(enc_pos, axis=0)
        return self.encoder(x)

    def _decode(self, F, dec_in_scaled, memory, dec_pos, length):
        y = self.dec_proj(F.expand_dims(dec_in_scaled, axis=2))
        y = y + F.expand_dims(
            F.slice_axis(dec_pos, axis=0, begin=0, end=length), axis=0)
        cm = self._causal_mask(F, length, dec_in_scaled.context)
        for cell in self.dec_cells:
            y = cell(y, memory, cm)
        return self.head(y)

    def hybrid_forward(self, F, past_target, future_target,
                       enc_pos=None, dec_pos=None):
        scale = _mean_abs_scale(F, past_target)
        past_scaled = past_target / scale
        future_scaled = future_target / scale
        memory = self._encode(F, past_scaled, enc_pos)
        # decoder input: last context value, then future[:-1]
        dec_in = F.concat(
            F.slice_axis(past_scaled, axis=1, begin=-1, end=None),
            F.slice_axis(future_scaled, axis=1, begin=0, end=-1), dim=1)
        mu, sigma = self._decode(F, dec_in, memory, dec_pos,
                                 self.prediction_length)
        nll = gaussian_nll(F, future_scaled, mu, sigma)
        return F.mean(nll, axis=1) + F.mean(F.log(scale), axis=1)

    def forecast(self, past_target):
        """Deterministic mean path via greedy autoregression."""
        from .. import ndarray as nd
        scale = _mean_abs_scale(nd, past_target)
        past_scaled = past_target / scale
        enc_pos = self.enc_pos.data(past_target.context)
        dec_pos = self.dec_pos.data(past_target.context)
        memory = self._encode(nd, past_scaled, enc_pos)
        dec_in = nd.slice_axis(past_scaled, axis=1, begin=-1, end=None)
        for t in range(self.prediction_length):
            mu, _ = self._decode(nd, dec_in, memory, dec_pos,
                                 t + 1)
            nxt = nd.slice_axis(mu, axis=1, begin=-1, end=None)
            dec_in = nd.concat(dec_in, nxt, dim=1)
        preds = nd.slice_axis(dec_in, axis=1, begin=1, end=None)
        return preds * scale
