"""BERT (capability target: GluonNLP BERT-base — SURVEY.md §2.6
"External zoos"; BASELINE config #3 "BERT-base pretraining
samples/sec/chip").

``BERTModel`` = embeddings (word + position + token-type) → N transformer
encoder layers (fused SDPA, flash on TPU) → pooler; ``BERTForPretrain``
adds the masked-LM head (decoder tied to word embeddings) and
next-sentence head, returning the summed pretraining loss.  The whole
pretraining step hybridizes/jits to one XLA program; data parallelism
comes from ``mx.parallel.DataParallelTrainer`` unchanged.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..gluon.block import HybridBlock
from ..gluon import nn
from ..gluon.contrib.nn import TransformerEncoder

__all__ = ["BERTModel", "BERTForPretrain", "bert_base", "bert_small",
           "bert_large", "get_bert"]


class BERTModel(HybridBlock):
    def __init__(self, vocab_size=30522, units=768, hidden_size=3072,
                 num_layers=12, num_heads=12, max_length=512,
                 type_vocab_size=2, dropout=0.1, remat=False,
                 scan_layers=False, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self.vocab_size = vocab_size
        with self.name_scope():
            self.word_embed = nn.Embedding(vocab_size, units,
                                           prefix="word_embed_")
            self.token_type_embed = nn.Embedding(type_vocab_size, units,
                                                 prefix="type_embed_")
            self.position_embed = self.params.get(
                "position_embed", shape=(max_length, units),
                init="normal")
            self.embed_layer_norm = nn.LayerNorm(in_channels=units)
            self.embed_dropout = nn.Dropout(dropout) if dropout else None
            self.encoder = TransformerEncoder(
                units, hidden_size, num_layers, num_heads,
                dropout=dropout, activation="gelu", remat=remat,
                scan_layers=scan_layers, prefix="enc_")
            self.pooler = nn.Dense(units, activation="tanh",
                                   in_units=units, flatten=False,
                                   prefix="pooler_")

    def hybrid_forward(self, F, inputs, token_types, valid_length=None,
                       position_embed=None):
        b, s = inputs.shape[0], inputs.shape[1]
        x = self.word_embed(inputs) + self.token_type_embed(token_types)
        x = x + F.expand_dims(
            F.slice_axis(position_embed, axis=0, begin=0, end=s), axis=0)
        x = self.embed_layer_norm(x)
        if self.embed_dropout is not None:
            x = self.embed_dropout(x)
        mask = None
        if valid_length is not None:
            # (B, 1, 1, S) key-padding mask broadcast over heads & queries
            steps = F.arange(0, s, ctx=inputs.context)
            mask = F.broadcast_lesser(
                F.expand_dims(steps, axis=0),
                F.expand_dims(valid_length.astype("float32"), axis=1))
            mask = F.expand_dims(F.expand_dims(mask, axis=1), axis=1)
        seq = self.encoder(x, mask)
        pooled = self.pooler(F.slice_axis(seq, axis=1, begin=0,
                                          end=1).reshape((b, -1)))
        return seq, pooled


class BERTForPretrain(HybridBlock):
    """MLM + NSP pretraining heads over BERTModel.

    ``decode_mlm=False`` skips the tied decode matmul and returns the
    pre-decode MLM hidden plus the tied weight and bias instead of
    logits, so the caller can fuse decode+CE with
    ``nd.chunked_softmax_ce_bias`` — the (B·M, V) logits (156 MB at
    bert_base b64/m20) are then never materialized.  The r5 on-chip
    ablation measured the decoded-logits MLM head at 18.6 ms of an
    81.3 ms step, far above its ~1 ms of matmul FLOPs — the gap is
    logits HBM traffic, which the fused path removes.
    """

    def __init__(self, bert: BERTModel, decode_mlm=True, **kwargs):
        super().__init__(**kwargs)
        units = bert._units
        self._decode_mlm = bool(decode_mlm)
        with self.name_scope():
            self.bert = bert
            self.mlm_dense = nn.Dense(units, activation=None,
                                      in_units=units, flatten=False,
                                      prefix="mlm_dense_")
            self.mlm_norm = nn.LayerNorm(in_channels=units)
            self.mlm_bias = self.params.get("mlm_bias",
                                            shape=(bert.vocab_size,),
                                            init="zeros")
            self.nsp_classifier = nn.Dense(2, in_units=units,
                                           prefix="nsp_")

    def hybrid_forward(self, F, inputs, token_types, valid_length,
                       masked_positions, mlm_bias=None):
        seq, pooled = self.bert(inputs, token_types, valid_length)
        mlm_in = _gather_positions(F, seq, masked_positions)
        h = self.mlm_dense(mlm_in)
        h = F.LeakyReLU(h, act_type="gelu")
        h = self.mlm_norm(h)
        # decode with TIED word-embedding weights: under CachedOp tracing
        # the weight's buffer holds the trace-time tracer, so gradients
        # flow to the embedding from both uses
        word_w = self.bert.word_embed.weight.data(h.context)
        nsp_scores = self.nsp_classifier(pooled)
        h2 = h.reshape((-1, h.shape[-1]))
        if not self._decode_mlm:
            # fused-CE contract: (hidden, nsp, tied weight, bias) —
            # feed the first/last two to chunked_softmax_ce_bias
            return h2, nsp_scores, word_w, mlm_bias
        mlm_scores = F.dot(h2, word_w, transpose_b=True) + mlm_bias
        return mlm_scores, nsp_scores


def _gather_positions(F, seq, positions):
    """seq (B,S,U), positions (B,M) → (B,M,U)."""
    b, s, u = seq.shape
    m = positions.shape[1]
    flat = seq.reshape((b * s, u))
    offset = F.arange(0, b, ctx=seq.context).reshape((b, 1)) * s
    idx = (positions.astype("float32") + offset).reshape((-1,))
    out = F.take(flat, idx, axis=0, mode="clip")
    return out.reshape((b, m, u))


_BERT_SPECS = {
    "bert_small": dict(units=256, hidden_size=1024, num_layers=4,
                       num_heads=4),
    "bert_base": dict(units=768, hidden_size=3072, num_layers=12,
                      num_heads=12),
    "bert_large": dict(units=1024, hidden_size=4096, num_layers=24,
                       num_heads=16),
}


def get_bert(name, vocab_size=30522, max_length=512, dropout=0.1,
             **kwargs):
    if name not in _BERT_SPECS:
        raise MXNetError(f"unknown bert config {name!r}; options "
                         f"{sorted(_BERT_SPECS)}")
    spec = dict(_BERT_SPECS[name])
    spec.update(kwargs)
    return BERTModel(vocab_size=vocab_size, max_length=max_length,
                     dropout=dropout, **spec)


def bert_base(**kwargs):
    return get_bert("bert_base", **kwargs)


def bert_small(**kwargs):
    return get_bert("bert_small", **kwargs)


def bert_large(**kwargs):
    return get_bert("bert_large", **kwargs)
