"""``mxnet_tpu.models``: model families beyond the in-repo gluon zoo
(capability targets from SURVEY.md §2.6: GluonNLP BERT, GluonTS
forecasters; Llama-family stretch)."""
from . import bert
from .bert import BERTModel, BERTForPretrain, bert_base, bert_small, \
    bert_large, get_bert
from . import forecast
from .forecast import DeepAR, TransformerForecaster
from . import llama
from . import ssd
from .ssd import SSD, ssd_tiny, MultiBoxLoss
from .llama import (LlamaModel, LlamaForCausalLM, get_llama,
                    llama_tiny, llama3_8b)
from . import hf_loader
from .hf_loader import (read_safetensors, write_safetensors,
                        load_hf_llama, export_hf_llama,
                        load_hf_bert, export_hf_bert)
from . import nmt
from .nmt import (TransformerNMT, BeamSearchScorer, BeamSearchSampler,
                  get_nmt, nmt_tiny, transformer_en_de_512)
from . import segmentation
from .segmentation import (FCN, DeepLabV3, SegmentationMetric,
                           SoftmaxSegLoss, fcn_tiny, deeplab_tiny)
from . import yolo
from .yolo import YOLOv3, YOLOv3Loss, yolo3_tiny
from . import pose
from .pose import (SimplePose, PoseHeatmapLoss, PCKMetric,
                   simple_pose_tiny)
from . import rcnn
from .rcnn import FasterRCNN, FasterRCNNLoss, faster_rcnn_tiny

__all__ = ["hf_loader", "read_safetensors", "write_safetensors",
           "load_hf_llama", "export_hf_llama", "load_hf_bert",
           "export_hf_bert",
           "ssd", "SSD", "ssd_tiny", "MultiBoxLoss",
           "bert", "BERTModel", "BERTForPretrain", "bert_base",
           "bert_small", "bert_large", "get_bert", "forecast",
           "DeepAR", "TransformerForecaster", "llama", "LlamaModel",
           "LlamaForCausalLM", "get_llama", "llama_tiny", "llama3_8b",
           "nmt", "TransformerNMT", "BeamSearchScorer",
           "BeamSearchSampler", "get_nmt", "nmt_tiny",
           "transformer_en_de_512", "segmentation", "FCN", "DeepLabV3",
           "SegmentationMetric", "SoftmaxSegLoss", "fcn_tiny",
           "deeplab_tiny", "yolo", "YOLOv3", "YOLOv3Loss",
           "yolo3_tiny", "pose", "SimplePose", "PoseHeatmapLoss",
           "PCKMetric", "simple_pose_tiny", "rcnn", "FasterRCNN",
           "FasterRCNNLoss", "faster_rcnn_tiny"]
