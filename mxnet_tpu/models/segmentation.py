"""Semantic segmentation family (capability target: GluonCV's
``FCN`` / ``DeepLabV3`` over zoo backbones — SURVEY.md §2.6 external
zoos; reference upstream example/fcn-xs and the GluonCV segmentation
scripts).

TPU-first notes: every head is static-shape convs + one bilinear
resize, so the whole forward (and the training loss with its ignore
mask) compiles to a single XLA program under ``hybridize()``.  The
dense per-pixel softmax is an MXU-shaped matmul (1x1 conv), and the
upsample is ``jax.image.resize`` — no gather scatter.
"""
from __future__ import annotations

import numpy as np

from ..gluon.block import HybridBlock
from ..gluon import nn
from ..metric import EvalMetric
from .feature import truncate_features

__all__ = ["FCN", "DeepLabV3", "SegmentationMetric", "fcn_tiny",
           "deeplab_tiny", "SoftmaxSegLoss"]


class _Backbone(HybridBlock):
    """Splits a zoo CNN's ``features`` into stem / stages so heads can
    tap the last two stage outputs (stride 16 and 32)."""

    def __init__(self, zoo_net, **kwargs):
        super().__init__(**kwargs)
        # the last two remaining blocks are stage N-1 (stride/16) and
        # stage N (stride/32); plain-list storage + one register_child
        # each (attribute assignment would auto-register a 2nd time)
        self._blocks = truncate_features(zoo_net)
        for i, b in enumerate(self._blocks):
            self.register_child(b, f"bb{i}")

    def hybrid_forward(self, F, x):
        for b in self._blocks[:-2]:
            x = b(x)
        c3 = self._blocks[-2](x)
        c4 = self._blocks[-1](c3)
        return c3, c4


class _FCNHead(HybridBlock):
    """GluonCV _FCNHead: 3x3 conv (C/4) + BN + relu + dropout + 1x1."""

    def __init__(self, in_channels, nclass, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        inter = max(in_channels // 4, 8)
        with self.name_scope():
            self.block = nn.HybridSequential()
            with self.block.name_scope():
                self.block.add(
                    nn.Conv2D(inter, 3, padding=1, use_bias=False,
                              in_channels=in_channels),
                    nn.BatchNorm(in_channels=inter),
                    nn.Activation("relu"))
                if dropout:
                    self.block.add(nn.Dropout(dropout))
                self.block.add(nn.Conv2D(nclass, 1, in_channels=inter))

    def hybrid_forward(self, F, x):
        return self.block(x)


class _SegBase(HybridBlock):
    """Shared FCN/DeepLab scaffolding: backbone taps, bilinear
    upsample back to input resolution, optional aux head (the GluonCV
    training recipe's deep supervision on stage 3)."""

    def __init__(self, nclass, backbone, aux=True, **kwargs):
        super().__init__(**kwargs)
        self.nclass = nclass
        self._aux = aux
        with self.name_scope():
            self.backbone = _Backbone(backbone, prefix="backbone_")

    def _upsample(self, F, x, size):
        return F.BilinearResize2D(x, height=size[0], width=size[1])

    def hybrid_forward(self, F, x):
        h, w = x.shape[2], x.shape[3]
        c3, c4 = self.backbone(x)
        out = self._upsample(F, self.head(c4), (h, w))
        if self._aux:
            return out, self._upsample(F, self.aux_head(c3), (h, w))
        return out

    def predict(self, x):
        """Class map (B, H, W) from the main head."""
        from .. import ndarray as nd
        out = self(x)
        if isinstance(out, tuple):
            out = out[0]
        return nd.argmax(out, axis=1)


class FCN(_SegBase):
    """FCN-32s with stage-3 auxiliary supervision (GluonCV ``FCN``).

    ``backbone`` is a fully-convolutional zoo classification net
    (resnet/mobilenet/densenet family; the classifier head is
    ignored); ``c3_channels``/``c4_channels`` name the channel counts
    of its last two stages — 256/512 for resnet18/34, 1024/2048 for
    resnet50+."""

    def __init__(self, nclass, backbone, c3_channels, c4_channels,
                 aux=True, dropout=0.1, **kwargs):
        super().__init__(nclass, backbone, aux=aux, **kwargs)
        with self.name_scope():
            self.head = _FCNHead(c4_channels, nclass, dropout,
                                 prefix="head_")
            if aux:
                self.aux_head = _FCNHead(c3_channels, nclass, dropout,
                                         prefix="aux_")


class _ASPP(HybridBlock):
    """Atrous spatial pyramid pooling (DeepLabV3): parallel 1x1 and
    dilated 3x3 branches + image-level pooling, fused by a 1x1."""

    def __init__(self, in_channels, out_channels=64,
                 rates=(6, 12, 18), **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.branches = []
            b0 = nn.HybridSequential(prefix="b0_")
            with b0.name_scope():
                b0.add(nn.Conv2D(out_channels, 1, use_bias=False,
                                 in_channels=in_channels),
                       nn.BatchNorm(in_channels=out_channels),
                       nn.Activation("relu"))
            self.branches.append(b0)
            self.register_child(b0, "b0")
            for i, r in enumerate(rates):
                br = nn.HybridSequential(prefix=f"b{i + 1}_")
                with br.name_scope():
                    br.add(nn.Conv2D(out_channels, 3, padding=r,
                                     dilation=r, use_bias=False,
                                     in_channels=in_channels),
                           nn.BatchNorm(in_channels=out_channels),
                           nn.Activation("relu"))
                self.branches.append(br)
                self.register_child(br, f"b{i + 1}")
            self.gap_conv = nn.Conv2D(out_channels, 1, use_bias=False,
                                      in_channels=in_channels,
                                      prefix="gap_")
            self.project = nn.Conv2D(
                out_channels, 1, use_bias=False,
                in_channels=out_channels * (len(rates) + 2),
                prefix="proj_")
            self.project_bn = nn.BatchNorm(in_channels=out_channels)

    def hybrid_forward(self, F, x):
        h, w = x.shape[2], x.shape[3]
        outs = [br(x) for br in self.branches]
        gap = F.mean(x, axis=(2, 3), keepdims=True)
        gap = F.Activation(self.gap_conv(gap), act_type="relu")
        outs.append(F.broadcast_to(gap, (x.shape[0], gap.shape[1],
                                         h, w)))
        y = self.project(F.concat(*outs, dim=1))
        return F.Activation(self.project_bn(y), act_type="relu")


class DeepLabV3(_SegBase):
    """DeepLabV3: ASPP over the stride-32 features + FCN aux head."""

    def __init__(self, nclass, backbone, c3_channels, c4_channels,
                 aspp_channels=64, rates=(6, 12, 18), aux=True,
                 dropout=0.1, **kwargs):
        super().__init__(nclass, backbone, aux=aux, **kwargs)
        with self.name_scope():
            aspp = _ASPP(c4_channels, aspp_channels, rates,
                         prefix="aspp_")
            head = nn.HybridSequential(prefix="head_")
            with head.name_scope():
                head.add(aspp)
                if dropout:
                    head.add(nn.Dropout(dropout))
                head.add(nn.Conv2D(nclass, 1,
                                   in_channels=aspp_channels))
            self.head = head
            if aux:
                self.aux_head = _FCNHead(c3_channels, nclass, dropout,
                                         prefix="aux_")


class SoftmaxSegLoss:
    """Per-pixel CE with ignore label and optional aux weighting (the
    GluonCV MixSoftmaxCrossEntropyLoss recipe)."""

    def __init__(self, ignore_label=-1, aux_weight=0.4):
        self.ignore_label = ignore_label
        self.aux_weight = aux_weight

    def __call__(self, outs, label):
        from .. import ndarray as nd
        if not isinstance(outs, tuple):
            outs = (outs,)

        def ce(logits):
            logp = nd.log_softmax(logits, axis=1)       # (B,C,H,W)
            keep = (label != self.ignore_label)
            safe = nd.where(keep, label,
                            nd.zeros_like(label)).astype("int32")
            picked = nd.pick(logp.transpose((0, 2, 3, 1)), safe,
                             axis=3)
            n = nd.maximum(nd.sum(keep), nd.ones((1,), ctx=label.context))
            return -nd.sum(picked * keep) / n

        loss = ce(outs[0])
        if len(outs) > 1:
            loss = loss + self.aux_weight * ce(outs[1])
        return loss


class SegmentationMetric(EvalMetric):
    """pixAcc + mIoU over streaming batches (GluonCV
    ``SegmentationMetric`` semantics; ignore label excluded).

    Subclasses :class:`mxnet_tpu.metric.EvalMetric`, so it composes
    with ``CompositeEvalMetric``/``get_name_value()``.  One confusion
    matrix accumulates per update via a single ``bincount`` pass
    (O(pixels), not O(nclass·pixels))."""

    def __init__(self, nclass, ignore_label=-1):
        self.nclass = nclass
        self.ignore_label = ignore_label
        # scalar base name (EvalMetric stringifies it); get() returns
        # the two-value list form, which get_name_value() zips
        super().__init__(name="segmentation")

    def reset(self):
        super().reset()
        # reset() runs from the base __init__, before our __init__
        # body assigns nclass
        n = getattr(self, "nclass", 0)
        self._cm = np.zeros((n, n), np.int64)

    def update(self, labels, preds):
        if not isinstance(labels, (list, tuple)):
            labels, preds = [labels], [preds]
        for label, pred in zip(labels, preds):
            label = np.asarray(label.asnumpy()
                               if hasattr(label, "asnumpy") else label,
                               np.int64)
            pred = np.asarray(pred.asnumpy()
                              if hasattr(pred, "asnumpy") else pred,
                              np.int64)
            keep = label != self.ignore_label
            li, pi = label[keep], pred[keep]
            self._cm += np.bincount(
                self.nclass * li + pi,
                minlength=self.nclass ** 2).reshape(self.nclass,
                                                    self.nclass)
            self.num_inst += int(keep.sum())

    def get(self):
        cm = self._cm
        inter = np.diag(cm)
        acc = float(inter.sum() / max(cm.sum(), 1))
        union = cm.sum(0) + cm.sum(1) - inter
        seen = union > 0
        iou = np.where(seen, inter / np.maximum(union, 1), np.nan)
        miou = float(np.nanmean(iou)) if seen.any() else 0.0
        return (["pixAcc", "mIoU"], [acc, miou])


def _tiny_backbone():
    from ..gluon.model_zoo import vision
    return vision.resnet18_v1(classes=10, thumbnail=True)


def fcn_tiny(nclass=3, aux=True):
    """Test-size FCN over thumbnail resnet18 (stages end at 256/512)."""
    return FCN(nclass, _tiny_backbone(), c3_channels=256,
               c4_channels=512, aux=aux)


def deeplab_tiny(nclass=3, aux=True):
    return DeepLabV3(nclass, _tiny_backbone(), c3_channels=256,
                     c4_channels=512, aspp_channels=32, aux=aux)
