"""YOLOv3 single-stage detector (capability target: GluonCV ``YOLOV3``
family — SURVEY.md §2.6 external zoos; reference-era analog
``example/ssd`` is covered by models/ssd.py, this adds the
anchor-prior/multi-scale-grid family).

TPU-first design — everything static-shape so train and decode each
compile to one XLA program:
- the three detection grids are fixed by the input size; anchors are
  compile-time constants;
- target assignment (best wh-IoU anchor per padded GT box) is computed
  as dense one-hot matrices and applied by reductions, not scatter —
  the (M, N) assignment matrix routes each GT to its grid slot, and
  colliding GTs resolve to the lowest index;
- the ignore mask (unmatched slots whose decoded box overlaps any GT
  above ``ignore_iou``) is a dense (N, M) IoU reduced over M.
Decode reuses the framework NMS (``_contrib_box_nms``).
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..gluon.block import HybridBlock
from ..gluon import nn

__all__ = ["YOLOv3", "YOLOv3Loss", "yolo3_tiny", "build_targets"]


def _conv_bn_leaky(channels, kernel, stride=1, prefix=""):
    out = nn.HybridSequential(prefix=prefix)
    with out.name_scope():
        out.add(nn.Conv2D(channels, kernel, strides=stride,
                          padding=kernel // 2, use_bias=False),
                nn.BatchNorm(),
                nn.LeakyReLU(0.1))
    return out


class YOLOv3(HybridBlock):
    """Darknet-style backbone + 3-scale YOLO heads.

    ``anchors``: list of 3 lists of (w, h) pairs in PIXELS of the
    input image, finest scale first (GluonCV convention reversed to
    ascending stride).  ``forward`` returns the raw head tensor
    (B, N, 5 + num_classes) with N = sum over scales of H*W*A, slot
    layout [tx, ty, tw, th, obj, cls...]; ``decode`` turns it into
    corner boxes + scores; the loss consumes it raw.
    """

    def __init__(self, num_classes, image_size=32, base_channels=16,
                 anchors=None, **kwargs):
        super().__init__(**kwargs)
        if image_size % 32:
            raise MXNetError("image_size must be a multiple of 32")
        self.num_classes = num_classes
        self._size = image_size
        if anchors is None:
            s = image_size
            anchors = [[(s * .08, s * .08), (s * .16, s * .12),
                        (s * .12, s * .20)],
                       [(s * .25, s * .25), (s * .40, s * .30),
                        (s * .30, s * .45)],
                       [(s * .55, s * .55), (s * .80, s * .60),
                        (s * .65, s * .85)]]
        if len(anchors) != 3:
            raise MXNetError("YOLOv3 uses exactly 3 scales")
        self._anchors = [[(float(w), float(h)) for w, h in a]
                         for a in anchors]
        self._strides = [8, 16, 32]
        with self.name_scope():
            self.stem = nn.HybridSequential(prefix="stem_")
            with self.stem.name_scope():
                self.stem.add(_conv_bn_leaky(base_channels, 3))
                for i in range(3):      # /8
                    self.stem.add(_conv_bn_leaky(
                        base_channels * 2 ** (i + 1), 3, stride=2))
            self.stage4 = _conv_bn_leaky(base_channels * 16, 3,
                                         stride=2, prefix="s4_")
            self.stage5 = _conv_bn_leaky(base_channels * 32, 3,
                                         stride=2, prefix="s5_")
            self.heads = []
            for i in range(3):
                a = len(self._anchors[i])
                head = nn.Conv2D(a * (5 + num_classes), 1,
                                 prefix=f"head{i}_")
                self.register_child(head, f"head{i}")
                self.heads.append(head)
        self._layout = self._build_layout()

    # ---- static slot geometry ---------------------------------------

    def _build_layout(self):
        """Per-slot constants: grid cell origin (pixels), anchor w/h,
        stride.  Shapes (N, 2)/(N, 2)/(N, 1), numpy float32."""
        cells, awh, strides = [], [], []
        for i, stride in enumerate(self._strides):
            g = self._size // stride
            ys, xs = np.mgrid[0:g, 0:g]
            # slot order: (cell row-major) x anchors — matches the
            # head reshape below
            cell = np.stack([xs, ys], -1).reshape(-1, 2)   # (g*g, 2)
            a = len(self._anchors[i])
            cells.append(np.repeat(cell, a, axis=0) * stride)
            awh.append(np.tile(np.asarray(self._anchors[i], "f4"),
                               (g * g, 1)).reshape(-1, 2))
            strides.append(np.full((g * g * a, 1), stride, "f4"))
        return (np.concatenate(cells).astype("f4"),
                np.concatenate(awh).astype("f4"),
                np.concatenate(strides).astype("f4"))

    @property
    def num_slots(self):
        return self._layout[0].shape[0]

    # ---- forward ----------------------------------------------------

    def hybrid_forward(self, F, x):
        c3 = self.stem(x)
        c4 = self.stage4(c3)
        c5 = self.stage5(c4)
        outs = []
        for feat, head, anchors in zip((c3, c4, c5), self.heads,
                                       self._anchors):
            y = head(feat)                     # (B, A*(5+C), H, W)
            b, _, h, w = y.shape
            a = len(anchors)
            y = y.reshape((b, a, 5 + self.num_classes, h * w))
            # slot order (cell, anchor): transpose to (B, HW, A, ch)
            y = y.transpose((0, 3, 1, 2)).reshape(
                (b, h * w * a, 5 + self.num_classes))
            outs.append(y)
        return F.concat(*outs, dim=1)          # (B, N, 5+C)

    def _layout_nd(self, ctx):
        from .. import ndarray as nd
        memo = getattr(self, "_layout_memo", None)
        if memo is None:
            memo = self._layout_memo = {}
        if ctx not in memo:
            cells, awh, strides = self._layout
            memo[ctx] = (nd.array(cells, ctx=ctx),
                         nd.array(awh, ctx=ctx),
                         nd.array(strides, ctx=ctx))
        return memo[ctx]

    def decode(self, preds, conf_thresh=0.01, nms_thresh=0.45,
               topk=100):
        """Raw preds → (B, N, 6) [cls_id, score, x1, y1, x2, y2] in
        [0,1] coords, NMS-suppressed rows set to -1 (framework NMS)."""
        from .. import ndarray as nd
        cells, awh, strides = self._layout_nd(preds.context)
        xy = (nd.sigmoid(preds[:, :, 0:2]) * strides + cells) \
            / self._size
        wh = nd.exp(nd.clip(preds[:, :, 2:4], -8.0, 8.0)) * awh \
            / self._size
        obj = nd.sigmoid(preds[:, :, 4:5])
        cls = nd.sigmoid(preds[:, :, 5:])
        scores = obj * cls                       # (B, N, C)
        cls_id = nd.argmax(scores, axis=-1, keepdims=True)
        best = nd.max(scores, axis=-1, keepdims=True)
        x1y1 = xy - wh / 2.0
        x2y2 = xy + wh / 2.0
        rows = nd.concat(cls_id.astype("float32"), best, x1y1, x2y2,
                         dim=-1)
        return nd.contrib.box_nms(rows, overlap_thresh=nms_thresh,
                                  valid_thresh=conf_thresh, topk=topk,
                                  id_index=0, score_index=1,
                                  coord_start=2, force_suppress=False)


def build_targets(net, labels, ctx):
    """Static-shape YOLOv3 target assignment.

    For each valid GT (cls >= 0), the matched slot is the one whose
    anchor has the best wh-IoU with the GT AND whose grid cell (at
    that slot's stride) contains the GT center.  Assignment is a dense
    (B, M, N) matrix; slot targets come out of matmuls, never scatter.
    Returns (obj_target (B,N), t_x, t_y, t_w, t_h, cls (B,N),
    x1, y1, x2, y2 (B,M, pixels), valid (B,M,1))."""
    from .. import ndarray as nd
    size = float(net._size)
    cells, awh, strides = net._layout_nd(ctx)
    n = net.num_slots
    valid = (labels[:, :, 0:1] >= 0)                       # (B, M, 1)
    gt_cls = nd.maximum(labels[:, :, 0],
                        nd.zeros_like(labels[:, :, 0]))
    x1, y1 = labels[:, :, 1] * size, labels[:, :, 2] * size
    x2, y2 = labels[:, :, 3] * size, labels[:, :, 4] * size
    gx, gy = (x1 + x2) / 2.0, (y1 + y2) / 2.0              # (B, M)
    gw = nd.maximum(x2 - x1, nd.ones_like(x1))
    gh = nd.maximum(y2 - y1, nd.ones_like(y1))

    # best anchor per GT by wh-IoU at the origin
    aw = awh[:, 0].reshape((1, 1, n))
    ah = awh[:, 1].reshape((1, 1, n))
    gw_ = gw.expand_dims(-1)
    gh_ = gh.expand_dims(-1)
    inter = nd.minimum(gw_, aw) * nd.minimum(gh_, ah)
    wh_iou = inter / (gw_ * gh_ + aw * ah - inter)         # (B, M, N)
    best_iou = nd.max(wh_iou, axis=-1, keepdims=True)
    is_best_shape = (wh_iou >= best_iou - 1e-9)
    cx = cells[:, 0].reshape((1, 1, n))
    cy = cells[:, 1].reshape((1, 1, n))
    st = strides[:, 0].reshape((1, 1, n))
    gx_ = gx.expand_dims(-1)
    gy_ = gy.expand_dims(-1)
    in_cell = ((gx_ >= cx) * (gx_ < cx + st)
               * (gy_ >= cy) * (gy_ < cy + st))
    assign = is_best_shape * in_cell * valid               # (B, M, N)

    obj_target = nd.max(assign, axis=1)                    # (B, N)
    # per-slot targets: when GTs collide on a slot, the LOWEST-index
    # GT wins (argmax of the 0/1 assignment column) — categorical ids
    # must never be averaged.  Unmatched slots read GT 0's values, but
    # every consumer multiplies by the positive mask first.
    first_gt = nd.argmax(assign, axis=1).astype("int32")   # (B, N)
    sel = nd.one_hot(first_gt, labels.shape[1])            # (B, N, M)

    def to_slots(v):
        return nd.sum(sel * v.expand_dims(1), axis=-1)

    sx, sy = to_slots(gx), to_slots(gy)
    sw, sh = to_slots(gw), to_slots(gh)
    scls = to_slots(gt_cls)
    cxs = cells[:, 0].reshape((1, n))
    cys = cells[:, 1].reshape((1, n))
    sts = strides[:, 0].reshape((1, n))
    t_x = nd.clip((sx - cxs) / sts, 1e-4, 1.0 - 1e-4)
    t_y = nd.clip((sy - cys) / sts, 1e-4, 1.0 - 1e-4)
    t_w = nd.log(nd.maximum(sw, nd.ones_like(sw))
                 / awh[:, 0].reshape((1, n)))
    t_h = nd.log(nd.maximum(sh, nd.ones_like(sh))
                 / awh[:, 1].reshape((1, n)))
    return (obj_target, t_x, t_y, t_w, t_h, scls, x1, y1, x2, y2,
            valid)


class YOLOv3Loss:
    """GluonCV YOLOV3Loss pairing: sigmoid-BCE for center offsets and
    objectness and classes, L1 for the log-scale wh; unmatched slots
    overlapping a GT above ``ignore_iou`` are excluded from the
    objectness loss.  ``labels`` are SSD-style (B, M, 5)
    [cls, x1, y1, x2, y2] in [0,1], padded rows cls = -1."""

    def __init__(self, net: YOLOv3, ignore_iou=0.7):
        self.net = net
        self.ignore_iou = float(ignore_iou)

    def __call__(self, preds, labels):
        from .. import ndarray as nd
        net = self.net
        cells, awh, strides = net._layout_nd(preds.context)
        b = labels.shape[0]
        (obj_target, t_x, t_y, t_w, t_h, scls, x1, y1, x2, y2,
         valid) = build_targets(net, labels, preds.context)

        # ---- ignore mask: decoded boxes vs GT IoU -------------------
        xy = (nd.sigmoid(preds[:, :, 0:2]) * strides + cells)
        wh = nd.exp(nd.clip(preds[:, :, 2:4], -8.0, 8.0)) * awh
        dec = nd.concat(xy - wh / 2, xy + wh / 2, dim=-1)  # px corner
        gtb = nd.concat(x1.expand_dims(-1), y1.expand_dims(-1),
                        x2.expand_dims(-1), y2.expand_dims(-1),
                        dim=-1)                            # (B, M, 4)
        ious = nd.contrib.box_iou(dec, gtb) \
            * valid.transpose((0, 2, 1))                   # (B, N, M)
        best_over_gt = nd.max(ious, axis=-1)               # (B, N)
        ignore = (best_over_gt > self.ignore_iou) * \
            (1.0 - obj_target)

        # ---- the loss pairing ---------------------------------------
        def bce(logit, target):
            return nd.relu(logit) - logit * target + \
                nd.log(1.0 + nd.exp(-nd.abs(logit)))

        obj_logit = preds[:, :, 4]
        obj_loss = bce(obj_logit, obj_target) * (1.0 - ignore)
        pos = obj_target
        npos = nd.maximum(nd.sum(pos), nd.ones((1,),
                                                ctx=preds.context))
        xy_loss = (bce(preds[:, :, 0], t_x)
                   + bce(preds[:, :, 1], t_y)) * pos
        wh_loss = (nd.abs(preds[:, :, 2] - t_w)
                   + nd.abs(preds[:, :, 3] - t_h)) * pos
        cls_onehot = nd.one_hot(scls.astype("int32"),
                                net.num_classes)
        cls_loss = nd.sum(bce(preds[:, :, 5:], cls_onehot),
                          axis=-1) * pos
        return (nd.sum(obj_loss) / (b * 1.0)
                + nd.sum(xy_loss + wh_loss + cls_loss) / npos)


def yolo3_tiny(num_classes=2, image_size=32, **kwargs):
    """Test-size YOLOv3 (32px input -> 4+2+1 cells x 3 anchors)."""
    return YOLOv3(num_classes, image_size=image_size,
                  base_channels=8, **kwargs)
