"""Llama-family decoder-only LMs (BASELINE config #5 "Llama-3-8B via
Gluon Blocks" — SURVEY.md §2.6 "External zoos" stretch target).

TPU-first design:

* RMSNorm / RoPE / fused SDPA are single registered ops (XLA fuses the
  rest); attention takes the flash path on chip, and the whole
  next-token-prediction step hybridizes to one XLA program.
* **Grouped-query attention**: ``num_kv_heads < num_heads`` shrinks the
  KV projections (Llama-3's layout); KV heads are broadcast to query
  heads inside the compiled graph.
* **Long context is first-class**: ``attn_impl="ring"`` routes
  attention through the SPMD ring-attention kernel over a
  sequence-parallel mesh axis (``sp``), so sequences shard across
  devices (SURVEY §5 long-context row).
* ``llama3_8b()`` builds the real 8B geometry — on a single v5e it is
  for sharded meshes/dryruns; ``llama_tiny`` trains in tests.
"""
from __future__ import annotations

from ..base import MXNetError
from ..gluon.block import HybridBlock
from ..gluon import nn

__all__ = ["LlamaModel", "LlamaForCausalLM", "RMSNormBlock",
           "get_llama", "llama_tiny", "llama3_8b"]


class RMSNormBlock(HybridBlock):
    """RMSNorm with learned gamma (Llama's norm; op: ``RMSNorm``)."""

    def __init__(self, units, eps=1e-5, **kwargs):
        super().__init__(**kwargs)
        self._eps = eps
        with self.name_scope():
            self.gamma = self.params.get("gamma", shape=(units,),
                                         init="ones")

    def hybrid_forward(self, F, x, gamma=None):
        return F.RMSNorm(x, gamma, eps=self._eps)


class _LlamaAttention(HybridBlock):
    def __init__(self, units, num_heads, num_kv_heads, rope_base,
                 attn_impl="sdpa", sp_axis="sp", sliding_window=None,
                 **kwargs):
        super().__init__(**kwargs)
        if units % num_heads:
            raise MXNetError(f"units {units} % num_heads {num_heads}")
        if num_heads % num_kv_heads:
            raise MXNetError("num_heads must be a multiple of "
                             "num_kv_heads (GQA groups)")
        if sliding_window is not None and attn_impl == "ring":
            raise MXNetError(
                "sliding_window with attn_impl='ring' is not "
                "supported: the band already caps per-query compute "
                "at O(W) — use the sdpa/flash path, or ring WITHOUT "
                "a window for full-causal sequence parallelism")
        self._h = num_heads
        self._kv = num_kv_heads
        self._d = units // num_heads
        self._base = rope_base
        self._impl = attn_impl
        self._sp_axis = sp_axis
        self._window = sliding_window
        with self.name_scope():
            self.q_proj = nn.Dense(num_heads * self._d, flatten=False,
                                   use_bias=False, in_units=units,
                                   prefix="q_")
            self.k_proj = nn.Dense(num_kv_heads * self._d, flatten=False,
                                   use_bias=False, in_units=units,
                                   prefix="k_")
            self.v_proj = nn.Dense(num_kv_heads * self._d, flatten=False,
                                   use_bias=False, in_units=units,
                                   prefix="v_")
            self.o_proj = nn.Dense(units, flatten=False, use_bias=False,
                                   in_units=num_heads * self._d,
                                   prefix="o_")

    def prefill(self, x, cache_k, cache_v, perm=None):
        """Batched prompt pass: full-sequence causal attention that
        also writes K/V for every prompt position into the caches —
        one program instead of S sequential steps.

        A cache SHORTER than the prompt is the rolling (sliding-
        window) buffer: slot j must hold the newest absolute position
        p ≡ j (mod C), so the prompt TAIL is written through the
        ``perm`` slot permutation (built ONCE by the caller — it
        depends only on (S, C), not the layer)."""
        from .. import ndarray as nd
        b, s = x.shape[0], x.shape[1]
        h, kv, d = self._h, self._kv, self._d
        q = nd.rope(self.q_proj(x).reshape((b, s, h, d)),
                    base=self._base)
        k = nd.rope(self.k_proj(x).reshape((b, s, kv, d)),
                    base=self._base)
        v = self.v_proj(x).reshape((b, s, kv, d))
        if perm is None:
            nd._cache_update(cache_k, k, offset=0, out=cache_k)
            nd._cache_update(cache_v, v, offset=0, out=cache_v)
        else:
            nd._cache_update(cache_k, nd.take(k, perm, axis=1),
                             offset=0, out=cache_k)
            nd._cache_update(cache_v, nd.take(v, perm, axis=1),
                             offset=0, out=cache_v)
        out = nd.dot_product_attention(q, k, v, causal=True,
                                       window=self._window)
        return self.o_proj(out.reshape((b, s, h * d)))

    def step(self, x, cache_k, cache_v, offset, mask, slot=None):
        """Incremental decode: x (B, 1, units), caches
        (B, C, KV, D) written in place; ``mask`` is the shared
        key-validity mask built once per decode_step.  ``offset`` is
        the ABSOLUTE position (drives RoPE); ``slot`` is the cache
        write index — ``offset % C`` for a rolling sliding-window
        buffer, defaulting to ``offset`` for the classic cache."""
        from .. import ndarray as nd
        b = x.shape[0]
        h, kv, d = self._h, self._kv, self._d
        q = nd.rope(self.q_proj(x).reshape((b, 1, h, d)),
                    offset=offset, base=self._base)
        k_t = nd.rope(self.k_proj(x).reshape((b, 1, kv, d)),
                      offset=offset, base=self._base)
        v_t = self.v_proj(x).reshape((b, 1, kv, d))
        # dynamic-offset scatter: one compiled program for every step
        if slot is None:
            slot = offset
        nd._cache_update(cache_k, k_t, offset=slot, out=cache_k)
        nd._cache_update(cache_v, v_t, offset=slot, out=cache_v)
        # GQA is native in dot_product_attention: the unrepeated cache
        # is attended directly (no (B, max_len, H, D) materialization)
        out = nd.dot_product_attention(q, cache_k, cache_v, mask,
                                       use_mask=True)
        return self.o_proj(out.reshape((b, 1, h * d)))

    def hybrid_forward(self, F, x):
        b, s = x.shape[0], x.shape[1]
        h, kv, d = self._h, self._kv, self._d
        q = F.rope(self.q_proj(x).reshape((b, s, h, d)),
                   base=self._base)
        k = F.rope(self.k_proj(x).reshape((b, s, kv, d)),
                   base=self._base)
        v = self.v_proj(x).reshape((b, s, kv, d))
        if self._impl == "ring":
            # the ring kernel groups query heads per KV head internally,
            # so only the small KV tensors travel the ICI ring
            from ..parallel.ring_attention import ring_attention_sharded
            out = ring_attention_sharded(q, k, v, axis=self._sp_axis,
                                         causal=True)
        else:
            # GQA is native in the attention op (grouped einsum)
            out = F.dot_product_attention(q, k, v, causal=True,
                                          window=self._window)
        return self.o_proj(out.reshape((b, s, h * d)))


class _LlamaMLP(HybridBlock):
    """SwiGLU feed-forward: down(silu(gate(x)) * up(x))."""

    def __init__(self, units, hidden, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.gate_proj = nn.Dense(hidden, flatten=False,
                                      use_bias=False, in_units=units,
                                      prefix="gate_")
            self.up_proj = nn.Dense(hidden, flatten=False,
                                    use_bias=False, in_units=units,
                                    prefix="up_")
            self.down_proj = nn.Dense(units, flatten=False,
                                      use_bias=False, in_units=hidden,
                                      prefix="down_")

    def hybrid_forward(self, F, x):
        return self.down_proj(F.silu(self.gate_proj(x))
                              * self.up_proj(x))


class _LlamaLayer(HybridBlock):
    def __init__(self, units, hidden, num_heads, num_kv_heads,
                 rope_base, attn_impl, sp_axis="sp",
                 sliding_window=None, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.input_norm = RMSNormBlock(units, prefix="innorm_")
            self.attn = _LlamaAttention(units, num_heads, num_kv_heads,
                                        rope_base, attn_impl,
                                        sp_axis=sp_axis,
                                        sliding_window=sliding_window,
                                        prefix="attn_")
            self.post_norm = RMSNormBlock(units, prefix="postnorm_")
            self.mlp = _LlamaMLP(units, hidden, prefix="mlp_")

    def hybrid_forward(self, F, x):
        x = x + self.attn(self.input_norm(x))
        return x + self.mlp(self.post_norm(x))

    def prefill(self, x, cache_k, cache_v, perm=None):
        x = x + self.attn.prefill(self.input_norm(x), cache_k, cache_v,
                                  perm=perm)
        return x + self.mlp(self.post_norm(x))

    def step(self, x, cache_k, cache_v, offset, mask, slot=None):
        x = x + self.attn.step(self.input_norm(x), cache_k, cache_v,
                               offset, mask, slot=slot)
        return x + self.mlp(self.post_norm(x))


class LlamaModel(HybridBlock):
    def __init__(self, vocab_size, units, hidden, num_layers, num_heads,
                 num_kv_heads=None, rope_base=10000.0,
                 attn_impl="sdpa", sp_axis="sp", sliding_window=None,
                 **kwargs):
        super().__init__(**kwargs)
        num_kv_heads = num_kv_heads or num_heads
        self._units = units
        self.vocab_size = vocab_size
        self.sliding_window = sliding_window
        with self.name_scope():
            self.embed = nn.Embedding(vocab_size, units,
                                      prefix="embed_")
            self.layers = []
            for i in range(num_layers):
                layer = _LlamaLayer(units, hidden, num_heads,
                                    num_kv_heads, rope_base, attn_impl,
                                    sp_axis=sp_axis,
                                    sliding_window=sliding_window,
                                    prefix=f"layer{i}_")
                self.register_child(layer, f"layer{i}")
                self.layers.append(layer)
            self.final_norm = RMSNormBlock(units, prefix="finalnorm_")

    def hybrid_forward(self, F, tokens):
        x = self.embed(tokens)
        for layer in self.layers:
            x = layer(x)
        return self.final_norm(x)


class LlamaForCausalLM(HybridBlock):
    """LM head over LlamaModel.

    ``tie_embeddings=True`` (default) shares the embedding matrix with
    the head — the Llama-3.2-1B/3B layout.  Llama-3-8B/70B use an
    UNTIED head: pass ``tie_embeddings=False`` with ``llama3_8b()``
    (that separate head adds ~0.53B params on top of the model's
    7.50B)."""

    def __init__(self, model: LlamaModel, tie_embeddings=True,
                 **kwargs):
        super().__init__(**kwargs)
        self._tied = tie_embeddings
        with self.name_scope():
            self.model = model
            if not tie_embeddings:
                self.lm_head = nn.Dense(model.vocab_size, flatten=False,
                                        use_bias=False,
                                        in_units=model._units,
                                        prefix="head_")

    def _head_weight(self, ctx):
        """The (V, U) LM-head matrix — the tied embedding or the
        untied head's Dense weight (one place for the branch: shared by
        hybrid_forward, _head, and the chunked loss)."""
        return (self.model.embed.weight.data(ctx) if self._tied
                else self.lm_head.weight.data(ctx))

    def hybrid_forward(self, F, tokens):
        h = self.model(tokens)
        if self._tied:
            w = self._head_weight(h.context)
            b, s, u = h.shape
            return F.dot(h.reshape((b * s, u)), w,
                         transpose_b=True).reshape(
                             (b, s, self.model.vocab_size))
        return self.lm_head(h)

    @staticmethod
    def _check_cache_dtype(dtype):
        """KV caches must be FLOAT: an integer cache dtype truncates
        every K/V write via _cache_update's cast-on-store (the
        historical int32-leak bug) and generates garbage silently."""
        import jax.numpy as jnp
        if not jnp.issubdtype(jnp.dtype(dtype), jnp.floating):
            raise MXNetError(
                f"KV cache dtype must be floating, got {dtype!r} "
                "(an int cache truncates every K/V write)")

    def _rolling_cache_len(self, max_len, rolling):
        """Cache length for (max_len, rolling) — ONE place for the
        rolling policy, shared by init_cache and generate_fused."""
        if not rolling:
            return max_len
        w = self.model.sliding_window
        if w is None:
            raise MXNetError(
                "rolling=True requires a model with sliding_window "
                "set (Mistral-style)")
        return min(int(w), max_len)

    def init_cache(self, batch_size, max_len, ctx=None, rolling=False,
                   dtype="float32"):
        """Preallocate per-layer KV caches (B, C, KV, D).

        ``rolling=True`` (sliding-window models only) allocates the
        Mistral rolling buffer: C = min(sliding_window, max_len), so
        decode memory is O(W) regardless of generation length —
        positions wrap via ``offset % C`` and out-of-window entries
        are overwritten exactly when they leave the band.

        ``dtype="bfloat16"`` halves cache HBM (and decode-time cache
        bandwidth — the dominant traffic at batch 1): K/V writes cast
        on store, attention math still accumulates f32 (mixed-dtype
        dots promote)."""
        from .. import ndarray as nd
        self._check_cache_dtype(dtype)
        cache_len = self._rolling_cache_len(max_len, rolling)
        caches = []
        for layer in self.model.layers:
            a = layer.attn
            shp = (batch_size, cache_len, a._kv, a._d)
            caches.append((nd.zeros(shp, ctx=ctx, dtype=dtype),
                           nd.zeros(shp, ctx=ctx, dtype=dtype)))
        return caches

    def _head(self, h):
        """LM-head projection shared by full-forward and decode paths."""
        from .. import ndarray as nd
        if self._tied:
            return nd.dot(h.reshape((-1, self.model._units)),
                          self._head_weight(h.context),
                          transpose_b=True)
        return self.lm_head(h).reshape((-1, self.model.vocab_size))

    def prefill(self, tokens, caches, last_pos=None):
        """Batched prompt pass filling the caches; returns the LAST
        position's logits (B, vocab).

        ``last_pos`` (an NDArray of per-row indices, shape (B,)) reads
        the logits at each row's OWN last real token instead of the
        final position — the right-padded bucket-prompt shape the
        serving plane feeds (pad rows beyond ``last_pos`` stay causal
        garbage that the decode-time validity mask never exposes)."""
        import numpy as np
        from .. import ndarray as nd
        x = self.model.embed(tokens)
        s = tokens.shape[1]
        c = caches[0][0].shape[1]
        perm = None
        if s > c:
            # rolling buffer shorter than the prompt: slot j holds the
            # newest position p ≡ j (mod C); one permutation for ALL
            # layers (it depends only on (S, C))
            start = s - c
            perm = nd.array(
                (start + (np.arange(c) - start) % c).astype("f4"),
                ctx=tokens.context)
        for layer, (ck, cv) in zip(self.model.layers, caches):
            x = layer.prefill(x, ck, cv, perm=perm)
        h = self.model.final_norm(x)
        if last_pos is None:
            return self._head(h[:, -1:])
        b = tokens.shape[0]
        # per-row gather as a one-hot contraction (hybridizable: no
        # host-side indices, positions ride as a dynamic input)
        pos = nd.arange(s, ctx=tokens.context).reshape((1, s))
        lp = last_pos.reshape((-1, 1))
        onehot = (pos <= lp) * (pos >= lp)             # (B, S) {0,1}
        sel = (h * onehot.reshape((b, s, 1))).sum(axis=1)
        return self._head(sel.reshape((b, 1, self.model._units)))

    def decode_step(self, token, caches, offset):
        """One incremental step: token (B, 1) → logits (B, vocab).

        ``offset`` may be a python number / 0-d NDArray (one shared
        position — the classic generation loop) or a (B,)-shaped
        NDArray giving every batch row its OWN absolute position (the
        continuous-batching serving shape: each slot decodes at its own
        depth; rope, the cache scatter, and the validity mask all
        specialize per row through the same dynamic-input path, so the
        mixed-depth batch still reuses ONE compiled program)."""
        from .. import ndarray as nd
        x = self.model.embed(token)
        # key-validity mask (pos <= offset), shared across all layers;
        # offset rides the dynamic-scalar path (nd.full would bake it
        # into static attrs and compile a fresh program per step)
        max_len = caches[0][0].shape[1]
        # build the mask on the token's device: the default (cpu) ctx
        # does not exist under the axon plugin, which registers itself
        # as the ONLY jax backend.  offset may be a python number (the
        # per-step path) or a 0-d NDArray (the fused on-device
        # generation loop carries it through lax.scan).
        off = offset if isinstance(offset, nd.NDArray) else float(offset)
        pos = nd.arange(max_len, ctx=token.context)
        w = self.model.sliding_window
        if isinstance(off, nd.NDArray) and off.ndim == 1:
            return self._decode_step_slots(x, caches, off, pos, w,
                                           max_len)
        slot = None
        if w is not None and max_len <= int(w):
            # ROLLING buffer (cache holds exactly the window): slot
            # j's absolute position is off - ((off - j) mod C), always
            # inside (off-C, off] — every WRITTEN slot is valid.
            # Validity is just "written": j <= off, or everything once
            # the buffer has wrapped (off >= C).
            c = float(max_len)
            slot = off % c
            # validity is just "slot written yet": pos <= off covers
            # both regimes — after the buffer wraps (off >= c) it is
            # all-true, which is exactly right (every slot then holds
            # a position inside the window)
            mask = pos <= off
        else:
            mask = pos <= off
            if w is not None:
                # classic full cache + sliding window: only the last W
                # entries are live — (off-W, off], same band the
                # prefill kernels apply
                mask = mask * (pos > off - float(w))
        mask = mask.reshape((1, 1, 1, max_len))
        for layer, (ck, cv) in zip(self.model.layers, caches):
            x = layer.step(x, ck, cv, offset, mask, slot=slot)
        h = self.model.final_norm(x)
        return self._head(h)

    def _decode_step_slots(self, x, caches, off, pos, w, max_len):
        """Per-slot decode body: ``off`` is (B,) absolute positions.
        Same math as the shared-offset path, with the mask, rope
        offsets, and cache-scatter slots specialized PER ROW (rope and
        ``_cache_update`` broadcast a (B,)-shaped dynamic offset).
        Rows are independent in attention, so one slot's cache garbage
        (an evicted request) can never reach another's logits."""
        b = x.shape[0]
        posr = pos.reshape((1, max_len))
        offv = off.reshape((-1, 1))
        slot = None
        if w is not None and max_len <= int(w):
            # rolling buffer: identical policy to the shared path,
            # elementwise over slots
            slot = off % float(max_len)
            mask = posr <= offv
        else:
            mask = posr <= offv
            if w is not None:
                mask = mask * (posr > offv - float(w))
        mask = mask.reshape((b, 1, 1, max_len))
        for layer, (ck, cv) in zip(self.model.layers, caches):
            x = layer.step(x, ck, cv, off, mask, slot=slot)
        h = self.model.final_norm(x)
        return self._head(h)

    def generate(self, tokens, max_new_tokens, temperature=0.0,
                 top_k=0, seed=0, rolling=False,
                 cache_dtype="float32"):
        """Autoregressive generation with a KV cache.

        tokens: (B, S) prompt NDArray.  Greedy when ``temperature=0``;
        otherwise softmax sampling with optional top-k truncation.
        Each step reuses ONE compiled program — positions ride the
        dynamic rope offset and the cache mask, so nothing recompiles
        as the sequence grows.  ``rolling=True`` (sliding-window
        models) bounds cache memory at O(W) via the rolling buffer.
        Returns (B, S + max_new_tokens).
        """
        import numpy as np
        from .. import ndarray as nd
        b, s = tokens.shape
        max_len = s + max_new_tokens
        caches = self.init_cache(b, max_len, ctx=tokens.context,
                                 rolling=rolling, dtype=cache_dtype)
        rng = np.random.RandomState(seed)
        out_tokens = [tokens.asnumpy()]
        logits = self.prefill(tokens, caches)  # one batched program
        for step_i in range(max_new_tokens):
            # float64 softmax: float32 normalization residue can make
            # np.random.choice reject the distribution
            lg = logits.asnumpy().astype(np.float64)
            if temperature and temperature > 0:
                lg = lg / temperature
                if top_k and top_k > 0:
                    kk = min(int(top_k), lg.shape[-1])
                    kth = np.sort(lg, axis=-1)[:, -kk][:, None]
                    lg = np.where(lg < kth, -np.inf, lg)
                p = np.exp(lg - lg.max(-1, keepdims=True))
                p /= p.sum(-1, keepdims=True)
                nxt = np.stack([rng.choice(p.shape[1], p=p[i])
                                for i in range(b)])
            else:
                nxt = lg.argmax(-1)
            host_tok = nxt.astype("float32").reshape(b, 1)
            out_tokens.append(host_tok)  # host already has it
            cur = nd.array(host_tok, ctx=tokens.context)
            if step_i < max_new_tokens - 1:  # last logits never read
                logits = self.decode_step(cur, caches, s + step_i)
        return nd.array(np.concatenate(out_tokens, axis=1),
                        ctx=tokens.context)

    def generate_beam(self, tokens, max_new_tokens, beam_size=4,
                      eos_id=None, alpha=1.0):
        """Beam-search generation over the KV-cache decoder.

        Reuses the generic :class:`~.nmt.BeamSearchSampler` (reference
        GluonNLP beam search): the flat beam axis is batch·beam, the
        per-layer caches are the reordered states, and the prompt is
        prefilled once per beam.  ``eos_id=None`` disables early stop
        (all beams run the full ``max_new_tokens``).  Returns
        ``(sequences (B, beam, S+<=N), scores (B, beam))`` sorted
        best-first, sequences INCLUDING the prompt.
        """
        import numpy as np
        from .. import ndarray as nd
        from .nmt import BeamSearchSampler, BeamSearchScorer

        b, s = tokens.shape
        k = int(beam_size)
        max_len = s + max_new_tokens
        # prefill ONCE per batch row, then replicate the filled caches
        # per beam (row-major repeat matches the sampler's i*k+j flat
        # layout) — K-fold less prompt compute than prefilling B*K
        # identical rows
        caches_b = self.init_cache(b, max_len, ctx=tokens.context)
        self.prefill(tokens, caches_b)
        caches = [(nd.repeat(ck, repeats=k, axis=0),
                   nd.repeat(cv, repeats=k, axis=0))
                  for ck, cv in caches_b]
        last = nd.repeat(tokens[:, -1:], repeats=k, axis=0)

        def decoder(tok, step_idx, states):
            # step 0 re-writes position s-1 with the same K/V (a
            # no-op) and reproduces the prefill logits — so the
            # sampler's uniform "decode from the start token" contract
            # needs no special first step
            lg = self.decode_step(tok, states, s - 1 + step_idx)
            return nd.log_softmax(lg, axis=-1), states

        sampler = BeamSearchSampler(
            beam_size=k,
            eos_id=-1 if eos_id is None else int(eos_id),
            scorer=BeamSearchScorer(alpha=alpha),
            max_length=max_new_tokens + 1)
        samples, scores, lens = sampler(decoder, last, caches, b)
        # samples begin with the (repeated) last prompt token: splice
        # the full prompt in front of the continuation
        samp = samples.asnumpy().astype(np.int64)[:, :, 1:]
        prompt = tokens.asnumpy().astype(np.int64)
        out = np.concatenate(
            [np.repeat(prompt[:, None], k, axis=1), samp], axis=2)
        return (nd.array(out.astype("f4"), ctx=tokens.context),
                scores)

    def generate_fused(self, tokens, max_new_tokens, temperature=0.0,
                       top_k=0, seed=0, rolling=False,
                       cache_dtype="float32"):
        """Whole-generation as ONE compiled program.

        Same contract as :meth:`generate`, but prefill + every decode
        step run inside a single jit with the sampling loop as
        ``lax.scan`` and the KV cache as the scan carry — the
        TPU-idiomatic serving shape.  The per-step path pays one host
        round trip per token (~30-40 ms through the axon tunnel, vs
        microseconds of compute for small models); this path pays one
        dispatch for the whole sequence.  Sampling uses on-device
        ``jax.random.categorical`` (seeded, reproducible) instead of
        the per-step path's host ``np.random`` — same distribution,
        different stream.  Compiled once per (batch, prompt_len,
        max_new_tokens, temperature>0, top_k) signature.
        """
        import jax
        import jax.numpy as jnp
        from jax import lax
        from .. import ndarray as nd
        from ..ndarray.ndarray import NDArray
        from ..gluon import block as block_mod

        ctx = tokens.context
        if max_new_tokens <= 0:
            # fresh array like generate() (callers may mutate the
            # result in place; aliasing the prompt would corrupt it)
            return tokens.copy()
        b, s = tokens.shape
        max_len = s + max_new_tokens
        params = [p.data(ctx) for p in
                  self.collect_params().values()]
        sample = bool(temperature and temperature > 0)
        # top_k only shapes the program when sampling — greedy ignores
        # it, and including it in the key would compile a duplicate
        kk = min(int(top_k), self.model.vocab_size) \
            if (top_k and sample) else 0

        self._check_cache_dtype(cache_dtype)
        cache_len = self._rolling_cache_len(max_len, rolling)
        cache_shapes = []
        for layer in self.model.layers:
            a = layer.attn
            cache_shapes.append((b, cache_len, a._kv, a._d))

        key = (b, s, max_new_tokens, sample, kk, rolling,
               str(cache_dtype), str(tokens.dtype))
        cache = getattr(self, "_gen_fused_cache", None)
        if cache is None:
            cache = self._gen_fused_cache = {}
        fn = cache.get(key)
        if fn is None:
            def traced(param_vals, tok_val, key_data, temp_val):
                with block_mod.tracing_scope(params, param_vals):
                    # caches hold activations in the declared cache
                    # dtype (a FLOAT dtype — int tokens once leaked
                    # int32 caches here, truncating every K/V write;
                    # bf16 halves decode cache bandwidth)
                    cdt = jnp.dtype(cache_dtype)
                    shells = [
                        (NDArray(jnp.zeros(shp, cdt), ctx=ctx),
                         NDArray(jnp.zeros(shp, cdt), ctx=ctx))
                        for shp in cache_shapes]
                    toks = NDArray(tok_val, ctx=ctx)
                    logits0 = self.prefill(toks, shells)._data

                    def pick(lg, k_step):
                        if not sample:
                            return jnp.argmax(lg, axis=-1)
                        lg = lg.astype(jnp.float32) / temp_val
                        if kk:
                            kth = lax.top_k(lg, kk)[0][:, -1:]
                            lg = jnp.where(lg < kth, -jnp.inf, lg)
                        return jax.random.categorical(k_step, lg)

                    def body(carry, _):
                        tok, off, k, flat = carry
                        k, sub = jax.random.split(k)
                        cshells = [
                            (NDArray(flat[2 * i], ctx=ctx),
                             NDArray(flat[2 * i + 1], ctx=ctx))
                            for i in range(len(cache_shapes))]
                        lg = self.decode_step(
                            NDArray(tok, ctx=ctx), cshells,
                            NDArray(off, ctx=ctx))._data
                        nxt = pick(lg, sub).astype(tok.dtype)
                        nxt = nxt.reshape((b, 1))
                        new_flat = tuple(
                            shell._data for pair in cshells
                            for shell in pair)
                        return (nxt, off + 1.0, k, new_flat), \
                            nxt[:, 0]

                    k0 = jax.random.wrap_key_data(key_data)
                    k0, sub0 = jax.random.split(k0)
                    first = pick(logits0, sub0).astype(
                        tok_val.dtype).reshape((b, 1))
                    flat0 = tuple(shell._data for pair in shells
                                  for shell in pair)
                    off0 = jnp.asarray(float(s), jnp.float32)
                    (_, _, _, _), toks_out = lax.scan(
                        body, (first, off0, k0, flat0), None,
                        length=max_new_tokens - 1) \
                        if max_new_tokens > 1 else ((None,) * 4,
                                                    jnp.zeros(
                                                        (0, b),
                                                        tok_val.dtype))
                    # sequence: prompt + first + scanned tokens
                    gen = jnp.concatenate(
                        [first, toks_out.T.astype(tok_val.dtype)],
                        axis=1)
                    return jnp.concatenate([tok_val, gen], axis=1)

            fn = cache[key] = jax.jit(traced)

        kd = jax.random.key_data(
            jax.random.key(int(seed)))
        out = fn([p._data for p in params], tokens._data, kd,
                 jnp.asarray(float(temperature or 1.0), jnp.float32))
        return NDArray(out, ctx=ctx)

    def loss(self, tokens, vocab_chunk=None):
        """Next-token cross-entropy over ``tokens`` (B, S) → scalar.

        ``vocab_chunk`` (or automatically at vocab ≥ 32768) streams
        the LM head through ``chunked_softmax_ce``: the (B·S, V)
        logits tensor — 16.8 GB f32 at Llama-3-8B b8 s4096, over a
        v5e's HBM — is never materialized; activation memory is
        O(B·S·chunk) with the slab recomputed in backward."""
        from .. import ndarray as nd
        from ..gluon.loss import SoftmaxCrossEntropyLoss
        v = self.model.vocab_size
        if vocab_chunk is None and v >= 32768:
            vocab_chunk = 8192
        if vocab_chunk:
            h = self.model(tokens)                     # (B, S, U)
            u = self.model._units
            hid = nd.slice_axis(h, axis=1, begin=0,
                                end=-1).reshape((-1, u))
            labels = nd.slice_axis(tokens, axis=1, begin=1,
                                   end=None).reshape((-1,))
            per_row = nd.chunked_softmax_ce(
                hid, self._head_weight(h.context), labels,
                chunk=int(vocab_chunk))
            return per_row.mean()
        logits = self(tokens)
        sce = SoftmaxCrossEntropyLoss()
        b, s, v = logits.shape
        pred = nd.slice_axis(logits, axis=1, begin=0,
                             end=-1).reshape((-1, v))
        labels = nd.slice_axis(tokens, axis=1, begin=1,
                               end=None).reshape((-1,))
        return sce(pred, labels).mean()


_LLAMA_SPECS = {
    # test-size config (trains in seconds on the CPU backend)
    "llama_tiny": dict(units=64, hidden=176, num_layers=2, num_heads=4,
                       num_kv_heads=2, rope_base=10000.0),
    # Llama-3-8B geometry (vocab passed by caller; default 128256)
    "llama3_8b": dict(units=4096, hidden=14336, num_layers=32,
                      num_heads=32, num_kv_heads=8,
                      rope_base=500000.0),
    # Mistral-style sliding-window test config: band of 32 positions —
    # the kernels skip out-of-band blocks, O(S·W) attention
    "mistral_tiny": dict(units=64, hidden=176, num_layers=2,
                         num_heads=4, num_kv_heads=2,
                         rope_base=10000.0, sliding_window=32),
    # Mistral-7B-v0.1 geometry (sliding_window=4096)
    "mistral_7b": dict(units=4096, hidden=14336, num_layers=32,
                       num_heads=32, num_kv_heads=8,
                       rope_base=10000.0, sliding_window=4096),
}


def get_llama(name, vocab_size=32000, attn_impl="sdpa", **kwargs):
    if name not in _LLAMA_SPECS:
        raise MXNetError(f"unknown llama config {name!r}; options "
                         f"{sorted(_LLAMA_SPECS)}")
    spec = dict(_LLAMA_SPECS[name])
    spec.update(kwargs)
    return LlamaModel(vocab_size=vocab_size, attn_impl=attn_impl,
                      **spec)


def llama_tiny(**kwargs):
    return get_llama("llama_tiny", **kwargs)


def llama3_8b(vocab_size=128256, **kwargs):
    return get_llama("llama3_8b", vocab_size=vocab_size, **kwargs)
