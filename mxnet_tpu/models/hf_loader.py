"""HuggingFace safetensors checkpoint I/O for the Llama/Mistral family.

The modern ecosystem analog of the dmlc ``.params`` reader
(``ndarray/legacy_io.py``, reference ``src/ndarray/ndarray.cc`` save
format): real Llama/Mistral weights ship as HF *safetensors* shards,
and a framework that cannot ingest them strands its model zoo.  Pure
stdlib + numpy/ml_dtypes — no safetensors package dependency.

Format (https spec, stable since v0.3): 8-byte LE u64 header length,
UTF-8 JSON header mapping tensor name → {dtype, shape, data_offsets},
then one contiguous byte buffer.  Offsets are relative to the buffer.

RoPE convention: HF Llama applies *rotate-half* (NeoX-style: pairs are
(i, i+d/2)); this framework's ``rope`` op rotates ADJACENT pairs
(GPT-J-style: (2i, 2i+1)).  With the per-head row permutation
P[2i]=i, P[2i+1]=i+d/2 applied to W_q/W_k, the identities
``rope_adj(P·x) == P·rope_neox(x)`` and ``(P·q)ᵀ(P·k) == qᵀk`` make
attention outputs bit-for-bit equivalent — checked by
``tests/test_hf_loader.py::test_rope_permutation_identity``.
"""
from __future__ import annotations

import json
import os
import re
import struct

import numpy as np

from ..base import MXNetError

__all__ = ["read_safetensors", "write_safetensors", "load_hf_llama",
           "export_hf_llama", "load_hf_bert", "export_hf_bert"]

_DTYPES = {
    "F64": np.float64, "F32": np.float32, "F16": np.float16,
    "I64": np.int64, "I32": np.int32, "I16": np.int16, "I8": np.int8,
    "U8": np.uint8, "BOOL": np.bool_,
}


def _bf16():
    import ml_dtypes
    return ml_dtypes.bfloat16


def _np_dtype(st_dtype):
    if st_dtype == "BF16":
        return _bf16()
    try:
        return _DTYPES[st_dtype]
    except KeyError:
        raise MXNetError(f"safetensors dtype {st_dtype!r} unsupported")


def _st_dtype(arr):
    if arr.dtype == _bf16():
        return "BF16"
    for name, dt in _DTYPES.items():
        if arr.dtype == dt:
            return name
    raise MXNetError(f"cannot write dtype {arr.dtype} to safetensors")


def read_safetensors(path, return_metadata=False):
    """path → {name: np.ndarray} (zero-copy views onto one mmap).

    With ``return_metadata=True`` returns ``(tensors, metadata_dict)``
    where metadata is the header's ``__metadata__`` entry ({} if
    absent)."""
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        (hlen,) = struct.unpack("<Q", f.read(8))
        if hlen > size - 8:
            # a bogus header length (e.g. another format's magic read
            # as a u64) must fail loudly, not as a MemoryError from a
            # multi-exabyte read
            raise MXNetError(
                f"{path}: not a safetensors file (header length "
                f"{hlen} exceeds file size {size})")
        try:
            header = json.loads(f.read(hlen).decode("utf-8"))
        except (UnicodeDecodeError, ValueError) as e:
            raise MXNetError(
                f"{path}: not a safetensors file ({e})") from e
    buf = np.memmap(path, dtype=np.uint8, mode="r", offset=8 + hlen)
    out = {}
    for name, spec in header.items():
        if name == "__metadata__":
            continue
        dt = _np_dtype(spec["dtype"])
        lo, hi = spec["data_offsets"]
        # a truncated or malformed shard must keep the MXNetError
        # contract the header checks establish — not surface as a raw
        # ValueError from np.frombuffer, or silently alias overlapping
        # views (ADVICE r4)
        if not (0 <= lo <= hi <= buf.size):
            raise MXNetError(
                f"{path}: tensor {name!r} data_offsets [{lo}, {hi}) "
                f"out of bounds for {buf.size}-byte data section "
                f"(truncated or malformed shard?)")
        want = (np.dtype(dt).itemsize
                * int(np.prod(spec["shape"], dtype=np.int64)))
        if hi - lo != want:
            raise MXNetError(
                f"{path}: tensor {name!r} data_offsets span "
                f"{hi - lo} bytes but dtype {spec['dtype']} × shape "
                f"{spec['shape']} needs {want}")
        out[name] = np.frombuffer(
            buf[lo:hi], dtype=dt).reshape(spec["shape"])
    if return_metadata:
        return out, header.get("__metadata__", {}) or {}
    return out


def write_safetensors(path, tensors, metadata=None):
    """{name: array-like} → one .safetensors file (sorted names,
    contiguous buffer — the canonical layout)."""
    header = {}
    if metadata:
        header["__metadata__"] = {str(k): str(v)
                                  for k, v in metadata.items()}
    blobs = []
    off = 0
    for name in sorted(tensors):
        arr = np.ascontiguousarray(tensors[name])
        nbytes = arr.nbytes
        header[name] = {"dtype": _st_dtype(arr),
                        "shape": list(arr.shape),
                        "data_offsets": [off, off + nbytes]}
        blobs.append(arr)
        off += nbytes
    hjson = json.dumps(header, separators=(",", ":")).encode("utf-8")
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(hjson)))
        f.write(hjson)
        for arr in blobs:
            f.write(arr.tobytes())


def _plan_shards(sizes, max_shard_bytes):
    """Greedy sorted-name packing of {name: nbytes} into shard groups;
    a single tensor larger than ``max_shard_bytes`` gets its own shard
    (tensors are never split).  Returns a list of name lists."""
    groups, cur, cur_bytes = [], [], 0
    for name in sorted(sizes):
        nb = int(sizes[name])
        if cur and cur_bytes + nb > max_shard_bytes:
            groups.append(cur)
            cur, cur_bytes = [], 0
        cur.append(name)
        cur_bytes += nb
    if cur:
        groups.append(cur)
    return groups


def write_safetensors_sharded(dir_path, tensors, max_shard_bytes,
                              metadata=None, materialize=None):
    """Write ``tensors`` as HF-layout shards under ``dir_path``:
    ``model-0000i-of-0000n.safetensors`` + ``model.safetensors.index.json``
    (the layout :func:`_shard_paths` consumes).  Returns the index path.

    STREAMING form: pass ``tensors`` as ``{name: (shape, dtype)}`` with
    ``materialize(name) -> np.ndarray`` — each tensor is materialized
    only while its shard is being written and dropped after, so peak
    host memory is one shard, not the model (the big-model save path;
    ``llama_spmd.save_llama_stacked`` gathers device shards this way).
    """
    os.makedirs(dir_path, exist_ok=True)
    if materialize is None:
        tensors = {k: np.ascontiguousarray(v)
                   for k, v in tensors.items()}
        sizes = {k: v.nbytes for k, v in tensors.items()}
        fetch = tensors.__getitem__
    else:
        sizes = {k: int(np.prod(shape, dtype=np.int64))
                 * np.dtype(dt).itemsize
                 for k, (shape, dt) in tensors.items()}
        fetch = materialize
    groups = _plan_shards(sizes, max_shard_bytes)
    n = len(groups)
    weight_map, total = {}, 0
    for i, names in enumerate(groups, start=1):
        shard = f"model-{i:05d}-of-{n:05d}.safetensors"
        group = {name: fetch(name) for name in names}
        write_safetensors(os.path.join(dir_path, shard), group,
                          metadata=metadata)
        for name in names:
            weight_map[name] = shard
            total += sizes[name]
        del group
    idx_path = os.path.join(dir_path, "model.safetensors.index.json")
    with open(idx_path, "w") as f:
        json.dump({"metadata": {"total_size": total},
                   "weight_map": weight_map}, f, indent=1)
    return idx_path


def _shard_paths(path):
    """A file, a sharded index json, or a directory → ordered shards."""
    if os.path.isdir(path):
        idx = os.path.join(path, "model.safetensors.index.json")
        if os.path.exists(idx):
            return _shard_paths(idx)
        one = os.path.join(path, "model.safetensors")
        if os.path.exists(one):
            return [one]
        shards = sorted(
            os.path.join(path, p) for p in os.listdir(path)
            if p.endswith(".safetensors"))
        if shards:
            return shards
        raise MXNetError(f"no .safetensors files under {path}")
    if path.endswith(".index.json"):
        with open(path) as f:
            idx = json.load(f)
        d = os.path.dirname(path)
        return [os.path.join(d, p)
                for p in sorted(set(idx["weight_map"].values()))]
    return [path]


def _rope_perm(d):
    """NeoX(half-split) → adjacent-pair row order for one head."""
    p = np.empty(d, np.int64)
    p[0::2] = np.arange(d // 2)
    p[1::2] = np.arange(d // 2) + d // 2
    return p


def _permute_qk(w, n_heads, d, invert=False):
    """Permute per-head rows of a (n_heads*d, U) projection between
    the HF rotate-half and this framework's adjacent-pair RoPE."""
    w = np.asarray(w).reshape(n_heads, d, -1)
    p = _rope_perm(d)
    if invert:
        inv = np.empty_like(p)
        inv[p] = np.arange(d)
        p = inv
    return w[:, p].reshape(n_heads * d, -1)


def _name_map(net):
    """our param name → (hf name, kind) for a LlamaForCausalLM."""
    model = net.model
    ours = {}
    for name in net.collect_params():
        if name.endswith("embed_weight"):
            ours[name] = ("model.embed_tokens.weight", "plain")
        elif name.endswith("finalnorm_gamma"):
            ours[name] = ("model.norm.weight", "plain")
        elif name.endswith("head_weight"):
            ours[name] = ("lm_head.weight", "plain")
        else:
            import re
            m = re.search(r"layer(\d+)_(\w+)$", name)
            if not m:
                raise MXNetError(f"unmapped param {name!r}")
            i, tail = int(m.group(1)), m.group(2)
            hf = f"model.layers.{i}."
            kind = "plain"
            if tail == "innorm_gamma":
                hf += "input_layernorm.weight"
            elif tail == "postnorm_gamma":
                hf += "post_attention_layernorm.weight"
            elif tail == "attn_q_weight":
                hf += "self_attn.q_proj.weight"
                kind = "q"
            elif tail == "attn_k_weight":
                hf += "self_attn.k_proj.weight"
                kind = "k"
            elif tail == "attn_v_weight":
                hf += "self_attn.v_proj.weight"
            elif tail == "attn_o_weight":
                hf += "self_attn.o_proj.weight"
            elif tail == "mlp_gate_weight":
                hf += "mlp.gate_proj.weight"
            elif tail == "mlp_up_weight":
                hf += "mlp.up_proj.weight"
            elif tail == "mlp_down_weight":
                hf += "mlp.down_proj.weight"
            else:
                raise MXNetError(f"unmapped param {name!r}")
            ours[name] = (hf, kind)
    return ours


def _read_all(path):
    tensors = {}
    for shard in _shard_paths(path):
        tensors.update(read_safetensors(shard))
    return tensors


def _assign_params(net, nmap, tensors, ctx, dtype, strict,
                   transform=None):
    """Shared load core for every family: missing-check (strict=False
    SKIPS missing params, keeping their initialization — the
    forgiving-load convention for partial checkpoints like pooler-less
    MLM exports), per-kind transform, shape check, set_data.  Returns
    the set of checkpoint names consumed."""
    from .. import nd

    used = set()
    for name, param in net.collect_params().items():
        hf_name, kind = nmap[name]
        if hf_name not in tensors:
            if not strict:
                continue
            raise MXNetError(
                f"checkpoint missing {hf_name!r} (for {name!r})")
        arr = np.asarray(tensors[hf_name], np.float32)
        if transform is not None:
            arr = transform(kind, arr)
        if tuple(arr.shape) != tuple(param.shape):
            raise MXNetError(
                f"{hf_name!r} shape {arr.shape} != {name!r} "
                f"shape {param.shape}")
        param.set_data(nd.array(arr.astype(dtype, copy=False),
                                ctx=ctx))
        used.add(hf_name)
    return used


def _check_extras(tensors, used, ignore):
    extra = {t for t in tensors if t not in used and not ignore(t)}
    if extra:
        raise MXNetError(
            f"checkpoint tensors with no destination: "
            f"{sorted(extra)[:8]}{'...' if len(extra) > 8 else ''}")


def load_hf_llama(net, path, ctx=None, dtype="float32",
                  strict=True):
    """Load HF Llama/Mistral safetensors weights into a
    ``LlamaForCausalLM`` (single file, sharded index, or directory).

    Tied-embedding models (Llama-3.2 style) may omit ``lm_head.weight``
    in the checkpoint; untied nets require it.  ``strict`` errors on
    missing/unused checkpoint tensors (rotary ``inv_freq`` buffers are
    always ignored — they are derived, not parameters); strict=False
    skips missing params (they keep their initialization).
    """
    tensors = _read_all(path)
    attn = net.model.layers[0].attn
    h, kv, d = attn._h, attn._kv, attn._d

    def transform(kind, arr):
        if kind == "q":
            return _permute_qk(arr, h, d)
        if kind == "k":
            return _permute_qk(arr, kv, d)
        return arr

    used = _assign_params(net, _name_map(net), tensors, ctx, dtype,
                          strict, transform)
    # a TIED net maps no param to lm_head.weight (there is no head
    # child); a checkpoint that nevertheless ships one is only
    # loadable if that head IS the embedding — an untied checkpoint
    # loaded into a tied net would otherwise silently drop its head
    if getattr(net, "_tied", False) and "lm_head.weight" in tensors \
            and "lm_head.weight" not in used:
        head = np.asarray(tensors["lm_head.weight"], np.float32)
        emb = np.asarray(tensors["model.embed_tokens.weight"],
                         np.float32)
        if head.shape != emb.shape or not np.allclose(head, emb):
            raise MXNetError(
                "checkpoint has an UNTIED lm_head.weight but the net "
                "was built with tie_embeddings=True — rebuild with "
                "tie_embeddings=False or the head would be silently "
                "replaced by the embedding")
        used.add("lm_head.weight")
    if strict:
        _check_extras(tensors, used, lambda t: "rotary_emb" in t)
    return net


def export_hf_llama(net, path, dtype=np.float32, metadata=None,
                    max_shard_bytes=None):
    """Write a ``LlamaForCausalLM``'s weights as ONE HF-layout
    safetensors file (inverse of :func:`load_hf_llama`, q/k rows
    permuted back to rotate-half order).  With ``max_shard_bytes``,
    ``path`` is a DIRECTORY and the weights are written as HF-style
    shards + index via :func:`write_safetensors_sharded`."""
    attn = net.model.layers[0].attn
    h, kv, d = attn._h, attn._kv, attn._d
    out = {}
    nmap = _name_map(net)
    for name, param in net.collect_params().items():
        hf_name, kind = nmap[name]
        arr = param.data().asnumpy().astype(dtype)
        if kind == "q":
            arr = _permute_qk(arr, h, d, invert=True)
        elif kind == "k":
            arr = _permute_qk(arr, kv, d, invert=True)
        out[hf_name] = arr
    meta = metadata or {"format": "pt", "producer": "mxnet_tpu"}
    if max_shard_bytes is not None:
        return write_safetensors_sharded(path, out, max_shard_bytes,
                                         metadata=meta)
    write_safetensors(path, out, metadata=meta)


# ---------------------------------------------------------------------------
# BERT (HF bert-base layout) — the flagship family
# ---------------------------------------------------------------------------

_BERT_LAYER_TABLE = {
    "multiheadattention0_query_weight": "attention.self.query.weight",
    "multiheadattention0_query_bias": "attention.self.query.bias",
    "multiheadattention0_key_weight": "attention.self.key.weight",
    "multiheadattention0_key_bias": "attention.self.key.bias",
    "multiheadattention0_value_weight": "attention.self.value.weight",
    "multiheadattention0_value_bias": "attention.self.value.bias",
    "multiheadattention0_out_weight": "attention.output.dense.weight",
    "multiheadattention0_out_bias": "attention.output.dense.bias",
    "positionwiseffn0_ffn1_weight": "intermediate.dense.weight",
    "positionwiseffn0_ffn1_bias": "intermediate.dense.bias",
    "positionwiseffn0_ffn2_weight": "output.dense.weight",
    "positionwiseffn0_ffn2_bias": "output.dense.bias",
    "layernorm0_gamma": "attention.output.LayerNorm.weight",
    "layernorm0_beta": "attention.output.LayerNorm.bias",
    "layernorm1_gamma": "output.LayerNorm.weight",
    "layernorm1_beta": "output.LayerNorm.bias",
}


def _bert_name_map(net):
    """our param name → HF name for a BERTModel (post-LN encoder:
    layernorm0 is the post-attention norm, layernorm1 the post-FFN —
    matching attention.output.LayerNorm / output.LayerNorm)."""
    out = {}
    for name in net.collect_params():
        m = re.search(r"enc_layer(\d+)_(\w+)$", name)
        if m:
            i, tail = int(m.group(1)), m.group(2)
            if tail not in _BERT_LAYER_TABLE:
                raise MXNetError(f"unmapped BERT param {name!r}")
            out[name] = (f"encoder.layer.{i}."
                         + _BERT_LAYER_TABLE[tail])
        elif name.endswith("position_embed"):
            out[name] = "embeddings.position_embeddings.weight"
        elif name.endswith("word_embed_weight"):
            out[name] = "embeddings.word_embeddings.weight"
        elif name.endswith("type_embed_weight"):
            out[name] = "embeddings.token_type_embeddings.weight"
        elif name.endswith("layernorm0_gamma"):
            out[name] = "embeddings.LayerNorm.weight"
        elif name.endswith("layernorm0_beta"):
            out[name] = "embeddings.LayerNorm.bias"
        elif name.endswith("pooler_weight"):
            out[name] = "pooler.dense.weight"
        elif name.endswith("pooler_bias"):
            out[name] = "pooler.dense.bias"
        else:
            raise MXNetError(f"unmapped BERT param {name!r}")
    return out


def load_hf_bert(net, path, ctx=None, dtype="float32", strict=True):
    """Load HF ``bert-base``-layout safetensors into a ``BERTModel``.

    Accepts checkpoints with or without the ``bert.`` task-model
    prefix (BertModel vs BertForPreTraining exports); task heads
    (``cls.*``) are ignored.  ``strict=False`` additionally skips
    MISSING params (e.g. pooler-less MLM exports keep the net's
    initialized pooler).  Shapes must already match — run one forward
    first so deferred shapes are resolved.
    """
    tensors = _read_all(path)
    # normalize the task-model prefix away
    if any(t.startswith("bert.") for t in tensors):
        tensors = {(t[5:] if t.startswith("bert.") else t): v
                   for t, v in tensors.items()}
    nmap = {k: (v, "plain") for k, v in _bert_name_map(net).items()}
    used = _assign_params(net, nmap, tensors, ctx, dtype, strict)
    if strict:
        _check_extras(tensors, used,
                      lambda t: t.startswith("cls.")
                      or "position_ids" in t)
    return net


def export_hf_bert(net, path, dtype=np.float32, metadata=None):
    """Write a ``BERTModel`` as HF bert-base-layout safetensors
    (inverse of :func:`load_hf_bert`)."""
    nmap = _bert_name_map(net)
    out = {}
    for name, param in net.collect_params().items():
        out[nmap[name]] = param.data().asnumpy().astype(dtype)
    write_safetensors(path, out, metadata=metadata or
                      {"format": "pt", "producer": "mxnet_tpu"})
