"""jax version-compatibility seams for the parallel package."""


def shard_map(f, mesh, in_specs, out_specs, check_vma=True):
    """``jax.shard_map`` across jax versions.

    jax >= 0.6 exposes ``jax.shard_map(..., check_vma=)``; 0.4.x only
    has ``jax.experimental.shard_map.shard_map(..., check_rep=)``.
    The 0.4.x replication checker is NOT the same check: without the
    varying-type system (``pvary`` annotations) its static inference
    false-positives on valid multi-axis programs (e.g. a pp-sharded
    pipeline body whose outputs it cannot prove tp-replicated), so the
    optional validation is disabled there — jax >= 0.6 keeps the real
    ``check_vma`` typing.
    """
    try:
        from jax import shard_map as _shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map as _rep
        return _rep(f, mesh=mesh, in_specs=in_specs,
                    out_specs=out_specs, check_rep=False)
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=check_vma)


def pvary(x, axis_names):
    """``lax.pvary`` across jax versions.

    Pre-0.6 jax has no varying-type system — inside ``shard_map`` every
    value is already per-device, so the marker is an identity there.
    """
    import jax.lax as lax
    pv = getattr(lax, "pvary", None)
    return pv(x, axis_names) if pv is not None else x


def pre_vma():
    """True on jax without the varying-type system (< 0.6).

    There, ``shard_map`` manual-mode autodiff transposes ``lax.psum``
    to ``psum(ct)`` unconditionally: the REPLICATED seed cotangent
    crossing a loss-closing psum once multiplies every gradient by the
    axis size (exactly once — downstream cotangents are varying, for
    which psum-of-ct IS the chain rule).  Callers that know their
    collective structure divide that factor back out (see
    ``pipeline_value_and_grad(grad_reduce_axes=...)``).
    """
    import jax.lax as lax
    return not hasattr(lax, "pvary")


def axis_size(axis_name):
    """``lax.axis_size`` across jax versions.

    0.4.x has no ``lax.axis_size``; there ``psum(1, axis)`` inside
    shard_map constant-folds to the same static int.
    """
    import jax.lax as lax
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)
