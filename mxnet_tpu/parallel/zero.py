"""ZeRO-1/2 cross-replica sharding of the weight update (host side).

The paper trail is "Automatic Cross-Replica Sharding of Weight Update
in Data-Parallel Training" (arXiv 2004.13336, PAPERS.md): replicated
data-parallel training makes every dp member run the SAME optimizer
update on the SAME reduced gradient — O(P) optimizer state and update
FLOPs per member.  Sharding the update over the dp axis drops both by
~dp x with no numerics change (the update is pointwise in the flat
parameter), which is the HBM ceiling ROADMAP item 1 names.

This module holds everything about the sharding that is NOT the traced
step program: stage selection (``MXTPU_ZERO_STAGE``), trainer
eligibility, the per-param flat-slice arithmetic (one record per
trainable param: ``[name, size, padded, chunk]``), sharded
optimizer-state creation, and the host-side layout conversions the
checkpoint/``save_states`` portability matrix needs (a ZeRO checkpoint
restores fp32-exact onto ANY dp size and onto ZeRO-off trainers, and
vice versa — pure reshapes of the flat f32 slices, element values
untouched).

The traced side — reduce-scatter (stage 2) or psum+slice (stage 1) of
the gradients, the fused multi-tensor update over each member's 1/N
slice, and the all-gather of updated weights, all inside the single
donated SPMD program — lives in ``parallel.trainer`` on the
``collectives.sharded_weight_update`` seam.  See docs/zero.md.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..base import MXNetError

__all__ = ["stage_from_env", "eligibility", "slice_record",
           "param_slice", "state_avals", "create_sharded_states",
           "gather_host", "reshard_host"]


def stage_from_env() -> int:
    """The requested ZeRO stage (``MXTPU_ZERO_STAGE``): 0 = off
    (replicated update), 1 = sharded optimizer state with an all-reduce
    gradient leg, 2 = sharded state AND a reduce-scatter gradient leg
    (half the gradient wire bytes).  Anything else raises."""
    from .. import envs
    stage = int(envs.get("MXTPU_ZERO_STAGE"))
    if stage not in (0, 1, 2):
        raise MXNetError(
            f"MXTPU_ZERO_STAGE must be 0, 1, or 2, got {stage}")
    return stage


def eligibility(trainer) -> Optional[str]:
    """None when this trainer can run the ZeRO-sharded update, else a
    human-readable reason.  Called at construction: an ineligible
    trainer with the env set WARNS and runs stage 0 (the replicated
    layout then trips the MXL310 runtime rule — a misconfigured plan
    silently burning HBM is exactly what that lint exists to catch)."""
    if not trainer._fuse_step or trainer._rule is None:
        return ("ZeRO needs fuse_step=True with a fused optimizer "
                "rule (the sharded update lives inside the single "
                "SPMD step program)")
    if not trainer._rule.pointwise:
        # the eligibility bit lives ON the rule (trainer._FusedRule
        # requires it explicitly), so adding a rule forces the
        # decision at the definition site — the sharded update applies
        # the rule to a 1/N slice, and per-tensor statistics (LAMB's
        # trust ratio over ||w||) would silently compute per SLICE
        return (f"optimizer {type(trainer.optimizer).__name__}'s "
                "fused rule is not pointwise in the flat parameter "
                "(per-tensor statistics would be computed per shard)")
    if trainer._param_sharding is not None:
        return ("ZeRO shards the UPDATE of dp-replicated params; a "
                "param_sharding (tensor-parallel) rule already shards "
                "the params themselves")
    cfg = trainer._compression_cfg
    if cfg is not None and cfg.get("type") != "int8":
        return ("2bit compression carries per-device full-size "
                "error-feedback residuals — incompatible with the "
                "sharded gradient leg (int8 composes: quantize -> "
                "scatter -> fp32 local accumulate)")
    if trainer.optimizer.multi_precision:
        return "multi-precision (fp16 master-weight) states are not " \
           "sharded by the ZeRO path"
    return None


def _size(shape) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n


def param_slice(shape, n_dp: int):
    """``(size, padded, chunk)`` for one param: flat length, padded to
    a multiple of ``n_dp``, and the per-member slice length.  Pure
    delegation to ``planner.flat_rows`` — the ONE definition of the
    flat ZeRO arithmetic (the ``state_avals`` /
    ``_sharding_tuples(mesh=)`` drift PR 11 warned about is gone by
    construction)."""
    from .planner import flat_rows
    return flat_rows(shape, n_dp)


def slice_record(params, tr_idx, n_dp: int) -> List[list]:
    """The warm-start/checkpoint manifest rows pinning the sharding
    layout: ``[name, size, padded, chunk]`` per trainable param, in
    ``tr_idx`` order.  Verified on ``warm_start`` (fail-open on
    mismatch) and consulted by the restore path's layout conversion."""
    out = []
    for i in tr_idx:
        d = params[i].data()
        size, padded, chunk = param_slice(d.shape, n_dp)
        out.append([params[i].name, size, padded, chunk])
    return out


def state_avals(params, tr_idx, states, n_dp: int):
    """Abstract ``(n_dp, chunk)`` f32 state layouts per trainable
    param, mirroring ``create_sharded_states`` leaf-for-leaf — what a
    live-resize pre-warm compiles against BEFORE any buffer moved (the
    target mesh's state rows do not exist yet, so the avals must be
    derived, not read).  ``states`` supplies the per-param leaf counts
    (the live tuples from the CURRENT layout — leaf count is
    dp-size-independent).  Returns a tuple of per-param tuples of
    ``jax.ShapeDtypeStruct``."""
    from .planner import zero_state_avals
    out = []
    for i in tr_idx:
        s = states[i]
        if s is None:
            out.append(())
            continue
        n_leaves = len(s) if isinstance(s, (list, tuple)) else 1
        out.append(zero_state_avals(params[i].data().shape, n_dp,
                                    n_leaves))
    return tuple(out)


def create_sharded_states(optimizer, index, param_nd, mesh,
                          dp_axis: str):
    """The sharded-layout twin of ``Optimizer.create_state``: a tuple
    of NDArray leaves, each a GLOBAL ``(n_dp, chunk)`` f32 zeros array
    placed ``P(dp_axis)`` so every member holds its ``(1, chunk)``
    slice — 1/N the replicated state's bytes per device.  The leaf
    COUNT comes from the optimizer's own ``create_state`` on a (1,)
    probe (SGD momentum, Adam m/v, ...), so save/load layouts stay in
    the class's hands.  Returns None when the optimizer is stateless
    for this param."""
    import jax
    from .. import ndarray as nd
    from ..ndarray.ndarray import NDArray
    from .collectives import sharded_update_state_init
    from .planner import zero_state_sharding

    probe = nd.zeros((1,), ctx=param_nd.context,
                     dtype=param_nd.dtype.name)
    template = optimizer.create_state(index, probe)
    if template is None:
        return None
    n_leaves = len(template) if isinstance(template, (list, tuple)) \
        else 1
    n_dp = int(mesh.shape[dp_axis])
    hosts = sharded_update_state_init(param_nd, n_leaves, n_dp)
    sharding = zero_state_sharding(mesh, dp_axis)
    return tuple(
        NDArray(jax.device_put(h, sharding), ctx=param_nd.context)
        for h in hosts)


# -- host-side layout conversions (checkpoint portability matrix) ----------

def gather_host(host: np.ndarray, shape) -> np.ndarray:
    """``(n, chunk)`` sharded rows -> the full state tensor of
    ``shape`` (trim the padding tail).  fp32-exact: a pure reshape."""
    host = np.asarray(host)
    size = _size(shape)
    flat = host.reshape(-1)
    if flat.size < size:
        raise MXNetError(
            f"sharded state rows hold {flat.size} elements, param "
            f"shape {tuple(shape)} needs {size}")
    return flat[:size].reshape(tuple(shape))


def reshard_host(host: np.ndarray, shape, n_dp: int) -> np.ndarray:
    """Any saved layout (full ``shape``, or ``(n_src, chunk_src)``
    rows from a different dp size) -> ``(n_dp, chunk)`` rows for THIS
    mesh.  fp32-exact: trim the old padding, re-pad for the new
    member count."""
    host = np.asarray(host)
    size, padded, chunk = param_slice(shape, n_dp)
    flat = host.reshape(-1)
    if flat.size < size:
        raise MXNetError(
            f"saved state holds {flat.size} elements, param shape "
            f"{tuple(shape)} needs {size}")
    flat = flat[:size].astype(np.float32, copy=False)
    if padded != size:
        flat = np.concatenate(
            [flat, np.zeros((padded - size,), np.float32)])
    return flat.reshape(n_dp, chunk)
