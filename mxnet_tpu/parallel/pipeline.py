"""Pipeline parallelism: GPipe-style microbatching over a ``pp`` mesh
axis.

Beyond-reference capability (the reference's closest analog is the
manual model-parallel LSTM example — SURVEY.md §2.3 "Pipeline parallel:
none"); built because the rebuild treats pp as a first-class mesh axis
alongside dp/tp/sp/ep.

TPU-first design: the schedule is SPMD — every device runs the same
program over its own stage's parameters (stages must therefore share
one structure, the transformer-stack case); activations hop stage→
stage with ``lax.ppermute`` (ICI neighbor transfer on a TPU torus) and
the M+P-1 step loop is statically unrolled so XLA overlaps each hop
with the next step's compute.  Differentiable end-to-end (the schedule
is plain traced code), so it composes with ``jax.grad`` and the fused
trainer.
"""
from __future__ import annotations

from functools import partial

import numpy as np

from ..base import MXNetError
from .mesh import current_mesh

__all__ = ["pipeline_apply", "pipeline_value_and_grad"]


def _local_schedule(params, xs, *, stage_fn, axis, n_microbatches):
    """Per-device body (runs inside shard_map).

    params: this stage's param pytree (leading stage dim of size 1);
    xs: (M, mb, ...) microbatches (replicated); returns (M, mb, ...) —
    nonzero only on the LAST stage, made global with a psum.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ._compat import axis_size
    n = axis_size(axis)
    p = lax.axis_index(axis)
    m = n_microbatches
    perm = [(i, (i + 1) % n) for i in range(n)]
    local_params = jax.tree_util.tree_map(lambda a: a[0], params)

    carry = jnp.zeros_like(xs[0])
    ys = jnp.zeros_like(xs)
    for t in range(m + n - 1):
        mb = t - p                      # microbatch this stage works on
        active = (mb >= 0) & (mb < m)
        idx = jnp.clip(mb, 0, m - 1)
        x_in = jnp.where(p == 0, xs[idx], carry)
        out = stage_fn(local_params, x_in)
        out = jnp.where(active, out, jnp.zeros_like(out))
        is_last = p == n - 1
        ys = ys.at[idx].add(jnp.where(active & is_last, out,
                                      jnp.zeros_like(out)))
        carry = lax.ppermute(out, axis, perm)
    # only the last stage holds results; sum-replicate across the axis
    return lax.psum(ys, axis)


_EXEC_CACHE = {}
_EXEC_CACHE_MAX = 64  # FIFO-bounded: a pathological caller cannot leak
                      # executables without bound


_HASH_MEMO = {}  # id -> (weakref, content hash): arrays hashed ONCE


def _capture_key(c):
    """Structural key for one closure capture."""
    if isinstance(c, (int, float, bool, str, bytes, type(None))):
        # include the type: ('v', 2) == ('v', 2.0) == ('v', True) would
        # otherwise alias executables compiled for different dtypes
        return ("v", type(c).__name__, c)
    try:
        import weakref
        memo = _HASH_MEMO.get(id(c))
        if memo is not None and memo[0]() is c:
            return memo[1]
        a = np.asarray(c)
        if a.dtype != object:
            key = ("a", a.shape, str(a.dtype), hash(a.tobytes()))
            try:
                # memoize per object so big device arrays pay the
                # device→host copy + hash ONCE, not per call
                _HASH_MEMO[id(c)] = (weakref.ref(c), key)
                if len(_HASH_MEMO) > 512:
                    _HASH_MEMO.pop(next(iter(_HASH_MEMO)))
            except TypeError:
                pass  # object not weakref-able: hash each call
            return key
    except Exception:
        pass
    return ("o", id(c))  # retained via the cache entry while cached


def _structural_fn_key(fn):
    """Key a callable structurally (code object + closure captures) so
    per-call lambdas with identical source hit the exec cache; closure
    captures are keyed by VALUE for scalars and by content hash for
    arrays (so equal re-created captures hit), falling back to
    identity (retained in the entry) for opaque objects.  Returns
    (key, captured) — captured must be retained alongside the cache
    entry so ids stay live."""
    code = getattr(fn, "__code__", None)
    closure = getattr(fn, "__closure__", None) or ()
    captured = tuple(c.cell_contents for c in closure)
    key = ((code.co_code, repr(code.co_consts),
            tuple(_capture_key(c) for c in captured))
           if code is not None else fn)
    return key, captured


def _resolve_specs(stacked_params, param_specs, axis):
    """Per-leaf PartitionSpecs: default P(axis); a caller-supplied
    pytree (matching stacked_params' structure) lets individual leaves
    carry EXTRA mesh axes — e.g. P('pp', 'tp') column-parallel layer
    weights, composing pipeline with tensor parallelism.  Every spec
    must keep ``axis`` on the leading (stage) dim."""
    import jax
    from jax.sharding import PartitionSpec as P

    if param_specs is None:
        return jax.tree_util.tree_map(lambda _: P(axis),
                                      stacked_params)
    def _check(_, s):
        if not len(s) or s[0] != axis:
            raise MXNetError(
                f"param_specs leaf {s} must shard the leading stage "
                f"dim over {axis!r}")

    jax.tree_util.tree_map(_check, stacked_params, param_specs)
    return param_specs


def _resolve_plan(plan, mesh, axis):
    """A ``planner.ShardingPlan`` supplies BOTH the named mesh and the
    stage axis (``plan.pp_axis``) — the planner is the one source of
    truth for axis names."""
    from .planner import resolve_plan_axis
    return resolve_plan_axis(plan, mesh, axis, "pp_axis")


def _validate_and_place(fname, stacked_params, x, n_microbatches,
                        mesh, axis, y=None, param_specs=None):
    """Shared arg validation + param placement for the pipeline entry
    points.  Returns (mesh, n_stages, placed params, specs)."""
    import jax
    from jax.sharding import NamedSharding

    mesh = mesh if mesh is not None else current_mesh()
    if axis not in mesh.axis_names:
        raise MXNetError(f"mesh has no axis {axis!r}")
    n = mesh.shape[axis]
    leaves = jax.tree_util.tree_leaves(stacked_params)
    if any(l.shape[0] != n for l in leaves):
        raise MXNetError(
            f"{fname}: stacked param leading dims "
            f"{[l.shape[0] for l in leaves]} must equal the {axis!r} "
            f"axis size {n}")
    if x.shape[0] % n_microbatches:
        raise MXNetError(
            f"batch {x.shape[0]} not divisible by n_microbatches "
            f"{n_microbatches}")
    if y is not None and y.shape[0] != x.shape[0]:
        raise MXNetError(
            f"{fname}: y batch {y.shape[0]} != x batch {x.shape[0]}")
    specs = _resolve_specs(stacked_params, param_specs, axis)
    params = jax.tree_util.tree_map(
        lambda l, s: jax.device_put(l, NamedSharding(mesh, s)),
        stacked_params, specs)
    return mesh, n, params, specs


def pipeline_apply(stage_fn, stacked_params, x, n_microbatches,
                   mesh=None, axis="pp", param_specs=None, plan=None):
    """Apply ``n_stages`` homogeneous stages as a GPipe pipeline.

    stage_fn(params_i, x_mb) -> y_mb (same shape as x_mb);
    stacked_params: pytree whose leaves have leading dim n_stages
    (sharded over ``axis``); x: (batch, ...) jax array — split into
    ``n_microbatches`` along dim 0.  Returns (batch, ...).
    ``param_specs`` (optional pytree of PartitionSpecs) lets leaves
    carry extra mesh axes — e.g. ``P('pp', 'tp')`` tensor-parallel
    weights, with ``stage_fn`` issuing the matching ``tp``
    collectives.

    The jitted executable is cached per (mesh, axis, stage_fn, shapes).
    ``plan`` (a ``parallel.ShardingPlan``) supplies the mesh AND the
    stage axis (``plan.pp_axis``) — the planner's axis names instead
    of an ad-hoc string.
    """
    import jax
    import jax.numpy as jnp
    from ._compat import shard_map
    from jax.sharding import PartitionSpec as P

    mesh, axis = _resolve_plan(plan, mesh, axis)
    mesh, n, params, specs = _validate_and_place(
        "pipeline_apply", stacked_params, x, n_microbatches, mesh,
        axis, param_specs=param_specs)
    leaves = jax.tree_util.tree_leaves(stacked_params)
    fn_key, captured = _structural_fn_key(stage_fn)
    key = (mesh, axis, fn_key, n_microbatches,
           tuple(l.shape for l in leaves), x.shape, str(x.dtype),
           tuple(str(s) for s in jax.tree_util.tree_leaves(
               specs, is_leaf=lambda s: isinstance(s, P))))
    entry = _EXEC_CACHE.get(key)
    fn = entry[0] if entry is not None else None
    if fn is None:
        rspec = P()
        body = shard_map(
            partial(_local_schedule, stage_fn=stage_fn, axis=axis,
                    n_microbatches=n_microbatches),
            mesh=mesh,
            in_specs=(specs, rspec),
            out_specs=rspec)

        def run(params, xb):
            xs = xb.reshape((n_microbatches,
                             xb.shape[0] // n_microbatches)
                            + xb.shape[1:])
            ys = body(params, xs)
            return ys.reshape(xb.shape)

        fn = jax.jit(run)
        # retain the captured objects so their ids stay live while the
        # cache entry exists (no id-reuse aliasing); FIFO-evict so the
        # cache cannot grow without bound
        while len(_EXEC_CACHE) >= _EXEC_CACHE_MAX:
            _EXEC_CACHE.pop(next(iter(_EXEC_CACHE)))
        _EXEC_CACHE[key] = (fn, captured)

    return fn(params, x)


def _local_1f1b(params, xs, ys, *, stage_fn, loss_fn, axis,
                n_microbatches, grad_fix=None):
    """Per-device 1F1B schedule (runs inside shard_map).

    Interleaved one-forward-one-backward over ``R = m + 2(n-1)``
    rounds: stage p forwards microbatch ``r - p`` and backwards
    microbatch ``r - 2(n-1) + p`` in round r, so the last stage runs
    its backward immediately after its forward (the 1F1B signature)
    and every stage holds at most ``2(n-1)+1`` stashed activations —
    bounded by PIPELINE DEPTH, not by the microbatch count (GPipe via
    plain autodiff keeps all m alive).

    The stash is a ring buffer of INPUT activations only (a jax array,
    so the traced per-stage slot index can dynamically select into
    it); the backward recomputes the stage forward under ``jax.vjp``
    — the standard remat trade (≈1 extra forward) that makes the
    schedule static-shape and SPMD-uniform.  Activations hop stage→
    stage with ``lax.ppermute`` (+1 forward, −1 cotangent), one
    neighbor transfer each way per round on a TPU torus.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ._compat import axis_size
    n = axis_size(axis)
    p = lax.axis_index(axis)
    m = n_microbatches
    local = jax.tree_util.tree_map(lambda a: a[0], params)
    perm_fwd = [(i, (i + 1) % n) for i in range(n)]
    perm_bwd = [((i + 1) % n, i) for i in range(n)]
    depth = 2 * (n - 1) + 1
    mb_shape = xs[0].shape

    ring = jnp.zeros((depth,) + mb_shape, xs.dtype)
    fcarry = jnp.zeros(mb_shape, xs.dtype)
    bcarry = jnp.zeros(mb_shape, xs.dtype)
    grad_acc = jax.tree_util.tree_map(
        lambda a: jnp.zeros_like(a, jnp.float32), local)
    loss_acc = jnp.zeros((), jnp.float32)
    is_last = p == n - 1

    R = m + 2 * (n - 1)
    for r in range(R):
        # ---- forward half-round
        f = r - p
        f_active = (f >= 0) & (f < m)
        fidx = jnp.clip(f, 0, m - 1)
        x_in = jnp.where(p == 0, xs[fidx], fcarry)
        out = stage_fn(local, x_in)
        # last stage: loss for THIS microbatch + cotangent wrt out
        loss_mb, loss_vjp = jax.vjp(
            lambda o: loss_fn(o, ys[fidx]), out)
        # the seed cotangent must carry the same device-varying type
        # as loss_mb under shard_map's manual-axes checking — derive
        # it from loss_mb instead of a fresh (replicated) constant
        (dy,) = loss_vjp(loss_mb * 0 + 1)
        loss_acc = loss_acc + jnp.where(
            f_active & is_last, loss_mb.astype(jnp.float32), 0.0)
        # stash the stage INPUT at this round's slot (static index)
        ring = ring.at[r % depth].set(
            jnp.where(f_active, x_in, ring[r % depth]))
        fcarry = lax.ppermute(out, axis, perm_fwd)

        # ---- backward half-round
        b = r - 2 * (n - 1) + p
        b_active = (b >= 0) & (b < m)
        # the slot this stage forwarded microbatch b in: traced per
        # stage, hence the array ring + dynamic take
        slot = jnp.mod(r - 2 * (n - 1) + 2 * p, depth)
        x_saved = jnp.take(ring, slot, axis=0)
        cot = jnp.where(is_last, dy, bcarry).astype(x_saved.dtype)
        _, stage_vjp = jax.vjp(stage_fn, local, x_saved)
        dparams, dx = stage_vjp(cot)
        grad_acc = jax.tree_util.tree_map(
            lambda g, d: g + jnp.where(
                b_active, d.astype(jnp.float32), 0.0),
            grad_acc, dparams)
        bcarry = lax.ppermute(dx, axis, perm_bwd)

    # loss lives on the last stage; grads are per-stage (stay sharded)
    # and return in the PARAM dtype (f32 accumulation is internal)
    loss = lax.psum(loss_acc, axis) / m
    if grad_fix is not None:
        # tensor-parallel closure (grad_reduce_axes): a leaf replicated
        # over a reduce axis came back as per-device PARTIALS — psum
        # restores the replication its out_spec claims; on pre-vma jax
        # every leaf additionally carries the seed-crossing psum
        # factor (see _compat.pre_vma), divided back out here
        psum_axes, scale = grad_fix
        gl, td = jax.tree_util.tree_flatten(grad_acc)
        gl = [lax.psum(g, ax) if ax else g
              for g, ax in zip(gl, psum_axes)]
        if scale != 1:
            gl = [g / scale for g in gl]
        grad_acc = jax.tree_util.tree_unflatten(td, gl)
    grads = jax.tree_util.tree_map(
        lambda g, a: (g[None] / m).astype(a.dtype), grad_acc, local)
    return loss, grads


def pipeline_value_and_grad(stage_fn, stacked_params, x, y, loss_fn,
                            n_microbatches, mesh=None, axis="pp",
                            param_specs=None, grad_reduce_axes=None,
                            plan=None):
    """1F1B pipeline training step: mean loss + stacked param grads.

    stage_fn(params_i, x_mb) -> y_mb (same shape); loss_fn(out_mb,
    y_mb) -> scalar (mean over the microbatch); stacked_params: pytree
    with leading dim n_stages sharded over ``axis``; x, y: (batch,
    ...) split into ``n_microbatches`` along dim 0.  Returns
    ``(loss, grads)`` with ``grads`` shaped/sharded like
    ``stacked_params`` — feed them to any optimizer.  ``param_specs``
    (optional pytree of PartitionSpecs) composes tensor parallelism
    into the pipeline: leaves may shard extra mesh axes (e.g.
    ``P('pp', 'tp')``) with ``stage_fn``/``loss_fn`` issuing the
    matching collectives; grads come back in the same layout.
    ``grad_reduce_axes`` names the NON-pipeline mesh axes those
    collectives close with ``psum`` (e.g. ``('tp',)`` for row-parallel
    projections + a tp-reduced loss): with it set, a param replicated
    over such an axis gets its per-device partial grads psummed back
    to true replication (a trained norm weight would otherwise hold
    DIVERGENT replicas — undefined on gather), and on pre-vma jax the
    seed-crossing psum factor (``_compat.pre_vma``) is divided out so
    grads match the unsharded reference exactly.

    ``plan`` (a ``parallel.ShardingPlan``) supplies the mesh and the
    stage axis (``plan.pp_axis``) — consumers of one plan never spell
    axis names twice.

    Compared with differentiating :func:`pipeline_apply`, the explicit
    1F1B schedule bounds in-flight activation memory by pipeline depth
    instead of microbatch count, at the cost of one recompute-forward
    per microbatch per stage (the jax.checkpoint trade).
    """
    import jax
    from ._compat import shard_map
    from jax.sharding import PartitionSpec as P

    mesh, axis = _resolve_plan(plan, mesh, axis)
    mesh, n, params, specs = _validate_and_place(
        "pipeline_value_and_grad", stacked_params, x, n_microbatches,
        mesh, axis, y=y, param_specs=param_specs)
    leaves = jax.tree_util.tree_leaves(stacked_params)
    sfn_key, s_cap = _structural_fn_key(stage_fn)
    lfn_key, l_cap = _structural_fn_key(loss_fn)
    # falsy entries mean "no extra axis" (e.g. a pp-only model passes
    # its tp_axis=None straight through) — filter them rather than
    # crash on mesh.shape[None]
    reduce_axes = tuple(a for a in (grad_reduce_axes or ()) if a)
    key = ("1f1b", mesh, axis, sfn_key, lfn_key, n_microbatches,
           tuple(l.shape for l in leaves),
           tuple(str(l.dtype) for l in leaves),
           x.shape, str(x.dtype), y.shape, str(y.dtype),
           reduce_axes,
           tuple(str(s) for s in jax.tree_util.tree_leaves(
               specs, is_leaf=lambda s: isinstance(s, P))))
    entry = _EXEC_CACHE.get(key)
    fn = entry[0] if entry is not None else None
    if fn is None:
        rspec = P()
        grad_fix = None
        if reduce_axes:
            from ._compat import pre_vma

            def _mentioned(spec):
                out = set()
                for e in tuple(spec or ()):
                    if e is None:
                        continue
                    out.update(e if isinstance(e, tuple) else (e,))
                return out

            spec_leaves = jax.tree_util.tree_leaves(
                specs, is_leaf=lambda s: isinstance(s, P))
            psum_axes = tuple(
                tuple(a for a in reduce_axes if a not in _mentioned(s))
                for s in spec_leaves)
            scale = 1
            if pre_vma():
                for a in reduce_axes:
                    scale *= int(mesh.shape[a])
            grad_fix = (psum_axes, scale)
        body = shard_map(
            partial(_local_1f1b, stage_fn=stage_fn, loss_fn=loss_fn,
                    axis=axis, n_microbatches=n_microbatches,
                    grad_fix=grad_fix),
            mesh=mesh,
            in_specs=(specs, rspec, rspec),
            out_specs=(rspec, specs))

        def run(params, xb, yb):
            mb = xb.shape[0] // n_microbatches
            xs = xb.reshape((n_microbatches, mb) + xb.shape[1:])
            ys = yb.reshape((n_microbatches, mb) + yb.shape[1:])
            return body(params, xs, ys)

        fn = jax.jit(run)
        while len(_EXEC_CACHE) >= _EXEC_CACHE_MAX:
            _EXEC_CACHE.pop(next(iter(_EXEC_CACHE)))
        _EXEC_CACHE[key] = (fn, (s_cap, l_cap))

    return fn(params, x, y)
