"""Pipeline parallelism: GPipe-style microbatching over a ``pp`` mesh
axis.

Beyond-reference capability (the reference's closest analog is the
manual model-parallel LSTM example — SURVEY.md §2.3 "Pipeline parallel:
none"); built because the rebuild treats pp as a first-class mesh axis
alongside dp/tp/sp/ep.

TPU-first design: the schedule is SPMD — every device runs the same
program over its own stage's parameters (stages must therefore share
one structure, the transformer-stack case); activations hop stage→
stage with ``lax.ppermute`` (ICI neighbor transfer on a TPU torus) and
the M+P-1 step loop is statically unrolled so XLA overlaps each hop
with the next step's compute.  Differentiable end-to-end (the schedule
is plain traced code), so it composes with ``jax.grad`` and the fused
trainer.
"""
from __future__ import annotations

from functools import partial

import numpy as np

from ..base import MXNetError
from .mesh import current_mesh

__all__ = ["pipeline_apply"]


def _local_schedule(params, xs, *, stage_fn, axis, n_microbatches):
    """Per-device body (runs inside shard_map).

    params: this stage's param pytree (leading stage dim of size 1);
    xs: (M, mb, ...) microbatches (replicated); returns (M, mb, ...) —
    nonzero only on the LAST stage, made global with a psum.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    n = lax.axis_size(axis)
    p = lax.axis_index(axis)
    m = n_microbatches
    perm = [(i, (i + 1) % n) for i in range(n)]
    local_params = jax.tree_util.tree_map(lambda a: a[0], params)

    carry = jnp.zeros_like(xs[0])
    ys = jnp.zeros_like(xs)
    for t in range(m + n - 1):
        mb = t - p                      # microbatch this stage works on
        active = (mb >= 0) & (mb < m)
        idx = jnp.clip(mb, 0, m - 1)
        x_in = jnp.where(p == 0, xs[idx], carry)
        out = stage_fn(local_params, x_in)
        out = jnp.where(active, out, jnp.zeros_like(out))
        is_last = p == n - 1
        ys = ys.at[idx].add(jnp.where(active & is_last, out,
                                      jnp.zeros_like(out)))
        carry = lax.ppermute(out, axis, perm)
    # only the last stage holds results; sum-replicate across the axis
    return lax.psum(ys, axis)


_EXEC_CACHE = {}
_EXEC_CACHE_MAX = 64  # FIFO-bounded: a pathological caller cannot leak
                      # executables without bound


_HASH_MEMO = {}  # id -> (weakref, content hash): arrays hashed ONCE


def _capture_key(c):
    """Structural key for one closure capture."""
    if isinstance(c, (int, float, bool, str, bytes, type(None))):
        # include the type: ('v', 2) == ('v', 2.0) == ('v', True) would
        # otherwise alias executables compiled for different dtypes
        return ("v", type(c).__name__, c)
    try:
        import weakref
        memo = _HASH_MEMO.get(id(c))
        if memo is not None and memo[0]() is c:
            return memo[1]
        a = np.asarray(c)
        if a.dtype != object:
            key = ("a", a.shape, str(a.dtype), hash(a.tobytes()))
            try:
                # memoize per object so big device arrays pay the
                # device→host copy + hash ONCE, not per call
                _HASH_MEMO[id(c)] = (weakref.ref(c), key)
                if len(_HASH_MEMO) > 512:
                    _HASH_MEMO.pop(next(iter(_HASH_MEMO)))
            except TypeError:
                pass  # object not weakref-able: hash each call
            return key
    except Exception:
        pass
    return ("o", id(c))  # retained via the cache entry while cached


def pipeline_apply(stage_fn, stacked_params, x, n_microbatches,
                   mesh=None, axis="pp"):
    """Apply ``n_stages`` homogeneous stages as a GPipe pipeline.

    stage_fn(params_i, x_mb) -> y_mb (same shape as x_mb);
    stacked_params: pytree whose leaves have leading dim n_stages
    (sharded over ``axis``); x: (batch, ...) jax array — split into
    ``n_microbatches`` along dim 0.  Returns (batch, ...).

    The jitted executable is cached per (mesh, axis, stage_fn, shapes).
    """
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = mesh if mesh is not None else current_mesh()
    if axis not in mesh.axis_names:
        raise MXNetError(f"mesh has no axis {axis!r}")
    n = mesh.shape[axis]
    leaves = jax.tree_util.tree_leaves(stacked_params)
    if any(l.shape[0] != n for l in leaves):
        raise MXNetError(
            f"pipeline_apply: stacked param leading dims "
            f"{[l.shape[0] for l in leaves]} must equal the {axis!r} "
            f"axis size {n}")
    if x.shape[0] % n_microbatches:
        raise MXNetError(
            f"batch {x.shape[0]} not divisible by n_microbatches "
            f"{n_microbatches}")

    # key stage_fn structurally (code object) so per-call lambdas with
    # identical source hit the cache; closure captures are keyed by
    # VALUE for scalars and by content hash for arrays (so equal
    # re-created captures hit), falling back to identity (retained in
    # the entry) for opaque objects
    code = getattr(stage_fn, "__code__", None)
    closure = getattr(stage_fn, "__closure__", None) or ()
    captured = tuple(c.cell_contents for c in closure)
    fn_key = ((code.co_code, repr(code.co_consts),
               tuple(_capture_key(c) for c in captured))
              if code is not None else stage_fn)
    key = (mesh, axis, fn_key, n_microbatches,
           tuple(l.shape for l in leaves), x.shape, str(x.dtype))
    entry = _EXEC_CACHE.get(key)
    fn = entry[0] if entry is not None else None
    if fn is None:
        pspec = P(axis)
        rspec = P()
        body = shard_map(
            partial(_local_schedule, stage_fn=stage_fn, axis=axis,
                    n_microbatches=n_microbatches),
            mesh=mesh,
            in_specs=(jax.tree_util.tree_map(lambda _: pspec,
                                             stacked_params), rspec),
            out_specs=rspec)

        def run(params, xb):
            xs = xb.reshape((n_microbatches,
                             xb.shape[0] // n_microbatches)
                            + xb.shape[1:])
            ys = body(params, xs)
            return ys.reshape(xb.shape)

        fn = jax.jit(run)
        # retain the captured objects so their ids stay live while the
        # cache entry exists (no id-reuse aliasing); FIFO-evict so the
        # cache cannot grow without bound
        while len(_EXEC_CACHE) >= _EXEC_CACHE_MAX:
            _EXEC_CACHE.pop(next(iter(_EXEC_CACHE)))
        _EXEC_CACHE[key] = (fn, captured)

    params = jax.tree_util.tree_map(
        lambda l: jax.device_put(l, NamedSharding(mesh, P(axis))),
        stacked_params)
    return fn(params, x)
