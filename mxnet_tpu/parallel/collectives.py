"""Collective wrappers over XLA's mesh collectives.

Capability parity: the reference's three comm transports (device rings/
trees in ``src/kvstore/comm.h``, NCCL allreduce in ``kvstore_nccl.h``,
ps-lite push/pull) all reduce to these four primitives on a TPU mesh; XLA
lowers them onto ICI (intra-slice) or DCN (cross-slice) automatically.

Two usage modes:

* **Inside shard_map/jit** (the hot path): the ``lax``-level functions
  ``psum/pmean/all_gather/ppermute/all_to_all`` taking an ``axis_name``.
* **Eager on NDArrays** (kvstore facade, tests): :func:`allreduce` — a
  jitted shard_map over the current mesh.
"""
from __future__ import annotations

from functools import partial

from ..base import MXNetError
from .mesh import current_mesh

__all__ = ["psum", "pmean", "all_gather", "ppermute", "all_to_all",
           "allreduce"]


def psum(x, axis_name):
    import jax.lax as lax
    return lax.psum(x, axis_name)


def pmean(x, axis_name):
    import jax.lax as lax
    return lax.pmean(x, axis_name)


def all_gather(x, axis_name, axis=0, tiled=True):
    import jax.lax as lax
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def ppermute(x, axis_name, perm):
    import jax.lax as lax
    return lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name, split_axis, concat_axis, tiled=True):
    import jax.lax as lax
    return lax.all_to_all(x, axis_name, split_axis, concat_axis,
                          tiled=tiled)


_ALLREDUCE_CACHE = {}


def allreduce(values, axis="dp", mesh=None, op="sum"):
    """Eager allreduce of per-device NDArray shards over a mesh axis.

    ``values``: list of NDArrays, one per device along ``axis`` (the
    kvstore ``device`` layout).  Returns the list of reduced NDArrays, one
    per input device.  The reduction runs as a single jitted shard_map —
    XLA emits one fused allreduce instead of the reference's hand-built
    reduce-broadcast tree.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from jax import shard_map

    from ..ndarray.ndarray import NDArray

    mesh = mesh if mesh is not None else current_mesh()
    n = mesh.shape[axis]
    if len(values) != n:
        raise MXNetError(
            f"allreduce: got {len(values)} shards for mesh axis "
            f"{axis!r} of size {n}")
    if op not in ("sum", "mean"):
        raise MXNetError(f"allreduce: unsupported op {op!r}")

    shape = values[0].shape
    dtype = values[0].dtype
    key = (mesh, axis, shape, str(dtype), op)
    fn = _ALLREDUCE_CACHE.get(key)
    if fn is None:
        spec = P(axis, *([None] * len(shape)))

        def _reduce(stacked):
            red = psum(stacked, axis) if op == "sum" else pmean(stacked,
                                                               axis)
            return red

        fn = jax.jit(shard_map(
            _reduce, mesh=mesh, in_specs=(spec,), out_specs=spec))
        _ALLREDUCE_CACHE[key] = fn

    sharding = NamedSharding(mesh, P(axis, *([None] * len(shape))))
    if len({v._data.device for v in values}) <= 1:
        stacked = jax.device_put(jnp.stack([v._data for v in values]),
                                 sharding)
    elif len(mesh.axis_names) == 1:
        # shards already live on their devices (kvstore 'device'
        # layout): assemble the global array in place, no host hop
        devs = list(mesh.devices.flat)
        arrs = [jax.device_put(v._data[None], d)
                for v, d in zip(values, devs)]
        stacked = jax.make_array_from_single_device_arrays(
            (n,) + tuple(shape), sharding, arrs)
    else:
        # multi-axis mesh with scattered shards: go through the host
        import numpy as _np
        stacked = jax.device_put(
            jnp.asarray(_np.stack([v.asnumpy() for v in values])),
            sharding)
    out = fn(stacked)
    return [NDArray(out[i], ctx=values[i].context)
            for i in range(len(values))]
