"""Collective wrappers over XLA's mesh collectives.

Capability parity: the reference's three comm transports (device rings/
trees in ``src/kvstore/comm.h``, NCCL allreduce in ``kvstore_nccl.h``,
ps-lite push/pull) all reduce to these four primitives on a TPU mesh; XLA
lowers them onto ICI (intra-slice) or DCN (cross-slice) automatically.

Two usage modes:

* **Inside shard_map/jit** (the hot path): the ``lax``-level functions
  ``psum/pmean/all_gather/ppermute/all_to_all`` taking an ``axis_name``.
* **Eager on NDArrays** (kvstore facade, tests): :func:`allreduce` — a
  jitted shard_map over the current mesh.
"""
from __future__ import annotations

from functools import partial

from ..base import MXNetError
from .mesh import current_mesh

__all__ = ["vocab_parallel_softmax_ce",
           "psum", "pmean", "all_gather", "ppermute", "all_to_all",
           "allreduce", "reduce_scatter", "quantized_psum",
           "quantized_reduce_scatter", "twobit_psum",
           "sharded_weight_update", "sharded_update_state_init"]


def psum(x, axis_name):
    import jax.lax as lax
    return lax.psum(x, axis_name)


def pmean(x, axis_name):
    import jax.lax as lax
    return lax.pmean(x, axis_name)


def all_gather(x, axis_name, axis=0, tiled=True):
    import jax.lax as lax
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, *, scatter_dimension=0, tiled=False):
    """Fused reduce-scatter over a mesh axis (inside shard_map/jit).

    Each of the N axis members contributes its ``x``; member i receives
    the cross-member SUM of slice i along ``scatter_dimension`` — the
    first half of a decomposed all-reduce, as ONE collective
    (``lax.psum_scatter``).  With ``tiled=False`` (default) the scatter
    dim must equal N and disappears from the result (``(N, c) ->
    (c,)``); ``tiled=True`` keeps it, leaving each member a 1/N-length
    slice.

    Ring cost (the accounting :func:`quantized_psum` documents): a ring
    reduce-scatter moves ``size * (N-1)/N`` bytes per member — exactly
    HALF a ring all-reduce, which pays the same again to all-gather the
    sums back.  That saved half is the ZeRO-2 gradient leg: shard the
    optimizer update (`sharded_weight_update`) and the gather half
    ships updated WEIGHTS instead of repeating the gradient bytes.
    """
    import jax.lax as lax
    return lax.psum_scatter(x, axis_name,
                            scatter_dimension=scatter_dimension,
                            tiled=tiled)


def ppermute(x, axis_name, perm):
    import jax.lax as lax
    return lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name, split_axis, concat_axis, tiled=True):
    import jax.lax as lax
    return lax.all_to_all(x, axis_name, split_axis, concat_axis,
                          tiled=tiled)


_ALLREDUCE_CACHE = {}


def allreduce(values, axis="dp", mesh=None, op="sum"):
    """Eager allreduce of per-device NDArray shards over a mesh axis.

    ``values``: list of NDArrays, one per device along ``axis`` (the
    kvstore ``device`` layout).  Returns the list of reduced NDArrays, one
    per input device.  The reduction runs as a single jitted shard_map —
    XLA emits one fused allreduce instead of the reference's hand-built
    reduce-broadcast tree.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ._compat import shard_map

    from ..ndarray.ndarray import NDArray

    mesh = mesh if mesh is not None else current_mesh()
    n = mesh.shape[axis]
    if len(values) != n:
        raise MXNetError(
            f"allreduce: got {len(values)} shards for mesh axis "
            f"{axis!r} of size {n}")
    if op not in ("sum", "mean"):
        raise MXNetError(f"allreduce: unsupported op {op!r}")

    shape = values[0].shape
    dtype = values[0].dtype
    key = (mesh, axis, shape, str(dtype), op)
    fn = _ALLREDUCE_CACHE.get(key)
    if fn is None:
        spec = P(axis, *([None] * len(shape)))

        def _reduce(stacked):
            red = psum(stacked, axis) if op == "sum" else pmean(stacked,
                                                               axis)
            return red

        fn = jax.jit(shard_map(
            _reduce, mesh=mesh, in_specs=(spec,), out_specs=spec))
        _ALLREDUCE_CACHE[key] = fn

    sharding = NamedSharding(mesh, P(axis, *([None] * len(shape))))
    if len({v._data.device for v in values}) <= 1:
        stacked = jax.device_put(jnp.stack([v._data for v in values]),
                                 sharding)
    elif len(mesh.axis_names) == 1:
        # shards already live on their devices (kvstore 'device'
        # layout): assemble the global array in place, no host hop
        devs = list(mesh.devices.flat)
        arrs = [jax.device_put(v._data[None], d)
                for v, d in zip(values, devs)]
        stacked = jax.make_array_from_single_device_arrays(
            (n,) + tuple(shape), sharding, arrs)
    else:
        # multi-axis mesh with scattered shards: go through the host
        import numpy as _np
        stacked = jax.device_put(
            jnp.asarray(_np.stack([v.asnumpy() for v in values])),
            sharding)
    out = fn(stacked)
    return [NDArray(out[i], ctx=values[i].context)
            for i in range(len(values))]


def quantized_psum(x, axis_name, *, bits=8):
    """int8-wire quantized allreduce (inside shard_map/jit).

    The SPMD analog of the reference's 2-bit gradient compression
    (``src/kvstore/gradient_compression.cc``; SURVEY.md §7 P6
    "quantized-allreduce ≙ gradient compression", cf. PAPERS.md
    EQuARX): a two-phase reduce-scatter/all-gather where BOTH phases
    move int8 — (1) each device splits into N chunks, quantizes each
    against its own absmax, and ``all_to_all``s the int8 chunks plus
    fp32 scalar scales; (2) each device dequant-sums its chunk,
    REQUANTIZES the partial sum, and int8-``all_gather``s it back.
    Wire bytes ≈ 2·size·1 vs a ring fp32 psum's ≈ 2·size·4 — a real
    4x, at the cost of two rounding stages.

    Deterministic, stateless, and differentiable-through (straight
    through estimator: gradients treat it as psum).  Error feedback is
    the caller's residual to keep, as in the reference.
    """
    import jax
    import jax.numpy as jnp
    import jax.lax as lax

    from ._compat import axis_size

    if bits != 8:
        raise MXNetError(f"quantized_psum: bits must be 8, got {bits}")
    qmax = float(2 ** (bits - 1) - 1)

    @jax.custom_vjp
    def _qpsum(v):
        n = axis_size(axis_name)
        flat = v.reshape(-1).astype(jnp.float32)
        padded = flat.size + ((-flat.size) % n)
        if padded != flat.size:
            flat = jnp.concatenate(
                [flat, jnp.zeros((padded - flat.size,), jnp.float32)])
        chunks = flat.reshape(n, -1)                       # (n, c)
        scale = jnp.maximum(jnp.max(jnp.abs(chunks), axis=1) / qmax,
                            1e-20)                         # (n,)
        q = jnp.clip(jnp.round(chunks / scale[:, None]), -qmax,
                     qmax).astype(jnp.int8)
        # phase 1: int8 chunks to their owner device + scalar scales
        q_x = lax.all_to_all(q, axis_name, 0, 0, tiled=True)
        s_x = lax.all_to_all(scale[:, None], axis_name, 0, 0,
                             tiled=True)                   # (n, 1)
        part = jnp.sum(q_x.astype(jnp.float32) * s_x, axis=0)  # (c,)
        # phase 2: requantize the partial sum, int8 all-gather back
        s2 = jnp.maximum(jnp.max(jnp.abs(part)) / qmax, 1e-20)
        q2 = jnp.clip(jnp.round(part / s2), -qmax,
                      qmax).astype(jnp.int8)
        allq = lax.all_gather(q2, axis_name, axis=0)       # (n, c)
        alls = lax.all_gather(s2, axis_name, axis=0)       # (n,)
        full = (allq.astype(jnp.float32)
                * alls[:, None]).reshape(-1)[:v.size]
        return full.reshape(v.shape).astype(v.dtype)

    def _fwd(v):
        return _qpsum(v), None

    def _bwd(_, g):
        # straight-through psum transpose: the all_gather-built output
        # is VARYING-typed, so its per-device cotangents accumulate
        # explicitly (psum), then re-mark varying for the input's type
        ct = lax.psum(g, axis_name)
        pcast = getattr(lax, "pcast", None)
        if pcast is not None:
            return (pcast(ct, (axis_name,), to="varying"),)
        from ._compat import pvary
        return (pvary(ct, (axis_name,)),)

    _qpsum.defvjp(_fwd, _bwd)
    return _qpsum(x)


def quantized_reduce_scatter(x, axis_name, *, bits=8):
    """int8-wire reduce-scatter: :func:`quantized_psum`'s REDUCE phase
    composed with the ZeRO gradient leg (inside shard_map/jit).

    quantize -> scatter -> fp32 local accumulate: each member splits
    ``x`` into N chunks, quantizes each against its own absmax
    (int8 codes + one fp32 scale per chunk), ``all_to_all``s the codes,
    and dequant-SUMS its own chunk in fp32.  Member i returns the fp32
    cross-member sum of chunk i, shaped ``(padded_size/N,)`` with
    ``padded_size = size + (-size) % N`` (padding tail carries zeros) —
    exactly the flat-slice layout :func:`sharded_weight_update`'s
    ``grad_reduce=`` callable contract expects.

    Wire bytes ≈ ``size * (N-1)/N`` at int8 vs a ring fp32
    reduce-scatter's ``4 * size * (N-1)/N`` — 4x, with ONE rounding
    stage (the fp32 accumulate never requantizes, unlike
    ``quantized_psum``'s gather phase, so the scattered sums are
    strictly more accurate than the allreduce's).
    """
    import jax.numpy as jnp
    import jax.lax as lax

    from ._compat import axis_size

    if bits != 8:
        raise MXNetError(
            f"quantized_reduce_scatter: bits must be 8, got {bits}")
    qmax = float(2 ** (bits - 1) - 1)
    n = axis_size(axis_name)
    flat = x.reshape(-1).astype(jnp.float32)
    padded = flat.size + ((-flat.size) % n)
    if padded != flat.size:
        flat = jnp.concatenate(
            [flat, jnp.zeros((padded - flat.size,), jnp.float32)])
    chunks = flat.reshape(n, -1)                       # (n, c)
    scale = jnp.maximum(jnp.max(jnp.abs(chunks), axis=1) / qmax,
                        1e-20)                         # (n,)
    q = jnp.clip(jnp.round(chunks / scale[:, None]), -qmax,
                 qmax).astype(jnp.int8)
    # int8 chunks to their owner member + the fp32 scalar scales
    q_x = lax.all_to_all(q, axis_name, 0, 0, tiled=True)
    s_x = lax.all_to_all(scale[:, None], axis_name, 0, 0,
                         tiled=True)                   # (n, 1)
    return jnp.sum(q_x.astype(jnp.float32) * s_x, axis=0)  # (c,)


def twobit_psum(x, axis_name, *, threshold=0.5, residual=None):
    """2-bit quantized allreduce with error feedback (inside shard_map).

    The SPMD spelling of the reference's ``dist_sync`` gradient
    compression (``src/kvstore/gradient_compression.cc``): each device
    adds its carried ``residual``, snaps every element to
    {-threshold, 0, +threshold}, and only PACKED codes cross the wire
    — four ternary codes per byte, genuinely 2 bits per element (the
    reference packs 16 per int32).  Like :func:`quantized_psum`, the
    exchange is two-phase so wire bytes stay O(size) regardless of
    axis width: (1) ``all_to_all`` the bit-packed chunks
    (size/4 bytes), (2) each device unpacks, sums its chunk (a sum of
    n ternary codes fits int8 exactly while n ≤ 127) and
    int8-``all_gather``s the partial back (size bytes).  Wire ≈
    1.25·size bytes vs a ring fp32 psum's ≈ 8·size — 6.4x.

    Returns ``(summed, new_residual)`` — the caller keeps the residual
    for the next step, which is what makes the quantization unbiased
    over time.
    """
    import jax.numpy as jnp
    import jax.lax as lax

    from ._compat import axis_size
    n = axis_size(axis_name)
    g = x if residual is None else x + residual
    codes = jnp.where(g >= threshold, 1,
                      jnp.where(g <= -threshold, -1, 0)).astype(jnp.int8)
    flat = codes.reshape(-1)
    # chunk count multiple of n, chunk length multiple of 4 (packing)
    chunk = -(-flat.size // n)
    chunk += (-chunk) % 4
    padded = chunk * n
    if padded != flat.size:
        flat = jnp.concatenate(
            [flat, jnp.zeros((padded - flat.size,), jnp.int8)])
    chunks = flat.reshape(n, -1)                            # (n, c)
    # phase 1: PACK {-1,0,1}+1 -> {0,1,2} into 2-bit lanes, 4/byte
    u = (chunks + 1).astype(jnp.uint8).reshape(n, -1, 4)
    packed = (u[..., 0] | (u[..., 1] << 2) | (u[..., 2] << 4)
              | (u[..., 3] << 6))                           # (n, c/4)
    px = lax.all_to_all(packed, axis_name, 0, 0, tiled=True)
    quads = jnp.stack([(px >> s) & 0x3 for s in (0, 2, 4, 6)],
                      axis=-1)
    cx = quads.reshape(n, -1).astype(jnp.int32) - 1         # (n, c)
    # partial sums are in [-n, n]: exact in int8 up to n == 127
    part_dtype = jnp.int8 if n <= 127 else jnp.int32
    part = cx.sum(axis=0).astype(part_dtype)
    # phase 2: narrow partial sums gathered back
    allp = lax.all_gather(part, axis_name, axis=0)          # (n, c)
    summed = (allp.astype(jnp.float32).reshape(-1)[:x.size]
              * threshold).reshape(x.shape)
    new_residual = g - codes.astype(g.dtype) * jnp.asarray(
        threshold, g.dtype)
    return summed.astype(x.dtype), new_residual


def vocab_parallel_softmax_ce(hidden, w_local, label, axis_name,
                              chunk=None):
    """Megatron-style vocab-parallel cross-entropy (inside shard_map).

    Dispatch rule (VERDICT r4 #4 — one documented entry point):
    ``ops.nn.chunked_softmax_ce`` is THE large-vocab CE; this function
    is its single-slab tp specialization, kept for callers whose
    per-shard slab (N, V/tp) already fits activation memory.  Pass
    ``chunk`` to stream even the local shard (tp × huge-vocab) — that
    delegates to ``chunked_softmax_ce(axis_name=...)``, same
    collective budget (one pmax + one fused psum), O(N·chunk)
    activations.

    The tensor-parallel LM head shards the (V, U) projection over
    ``axis_name`` by vocab rows; each rank computes its LOCAL logits
    slab (N, V/tp) and the softmax normalizer is assembled with ONE
    pmax + psum pair — the full (N, V) logits never exist on any
    device and the wire carries only (N,)-sized rows.  The label
    logit comes from whichever rank owns the label's row (everyone
    else contributes an exact zero).  Differentiable through the
    collectives (the vjp of psum is broadcast; the max subtraction
    cancels analytically), so dW stays sharded and dH is exact.

    hidden (N, U); w_local (V_local, U) — ranks tile the vocab in
    order (rank i owns rows [i·V_local, (i+1)·V_local)); label (N,)
    int.  Returns per-row loss (N,), f32.

    Reference analog: the kvstore sharded softmax has no upstream
    equivalent — this is the TPU-idiomatic replacement for replicating
    the full head on every data-parallel worker (SURVEY.md §7 P6).
    """
    import jax.numpy as jnp
    import jax.lax as lax

    if chunk is not None:
        from ..ops.nn import chunked_softmax_ce
        return chunked_softmax_ce(hidden, w_local, label, chunk=chunk,
                                  axis_name=axis_name)
    i = lax.axis_index(axis_name)
    v_local = w_local.shape[0]
    logits = jnp.dot(hidden, w_local.T,
                     preferred_element_type=jnp.float32)
    m = lax.pmax(lax.stop_gradient(logits).max(axis=1), axis_name)
    lbl = label.astype(jnp.int32)
    idx = lbl - i * jnp.int32(v_local)
    in_range = (idx >= 0) & (idx < v_local)
    picked = jnp.take_along_axis(
        logits, jnp.clip(idx, 0, v_local - 1)[:, None], axis=1)[:, 0]
    # ONE collective for both reductions: the normalizer partial sums
    # and the label-logit contributions ride the same psum (a second
    # psum would add a full collective latency per loss evaluation)
    s, lab = lax.psum(
        jnp.stack([jnp.exp(logits - m[:, None]).sum(axis=1),
                   jnp.where(in_range, picked, 0.0)]), axis_name)
    return m + jnp.log(s) - lab


def sharded_weight_update(param, grad, states, update_fn, axis_name,
                          *, grad_reduce="scatter"):
    """ZeRO-1 / cross-replica weight-update sharding (PAPERS.md:
    "Automatic Cross-Replica Sharding of Weight Update in
    Data-Parallel Training", arXiv 2004.13336 — the paper's XLA
    recipe, expressed at the collective level).

    Replicated data-parallel training makes every dp member do the
    SAME full optimizer update on the SAME summed gradient — O(P)
    optimizer state and update FLOPs per member.  This helper shards
    the update over ``axis_name`` instead:

      1. ``psum_scatter`` the per-member gradient: one fused
         reduce-scatter leaves each member the SUM of its 1/N slice
         (half the wire bytes of a psum — the all-gather half moves
         updated WEIGHTS below instead of gradients);
      2. apply ``update_fn`` on the slice — optimizer state lives
         ONLY as (size/N,) slices per member (adam m/v memory drops
         by N);
      3. ``all_gather`` the updated slices back into the full
         replicated parameter.

    Runs INSIDE shard_map/jit.  ``param`` (any shape, replicated over
    ``axis_name``); ``grad`` the LOCAL (un-reduced) gradient, same
    shape; ``states`` a tuple of (padded_size/N,)-shaped state slices
    (start from :func:`sharded_update_state_init`); ``update_fn``
    ``(p_slice, g_slice, *state_slices) -> (new_p_slice,
    new_state_slices)`` — flat f32 slices.  The flat length is padded
    to a multiple of N; padding tail slices carry zeros and update_fn
    must be pointwise in the slice (every standard optimizer is).
    Returns ``(new_param, new_state_slices)``.

    ``grad_reduce`` selects the gradient leg:

    * ``"scatter"`` (default, ZeRO-2): one fused ``psum_scatter`` —
      grads cross the wire once, sharded;
    * ``"local"`` (ZeRO-1, or a caller that already reduced): ``grad``
      is ALREADY the cross-member-reduced gradient, replicated — just
      slice the local chunk, no collective on this leg;
    * a callable ``(padded_flat_grad,) -> (chunk,)`` supplying its own
      reduce-scatter — e.g. :func:`quantized_reduce_scatter` for the
      int8-wire leg (quantize -> scatter -> fp32 local accumulate).
    """
    import jax.numpy as jnp
    import jax.lax as lax

    from ._compat import axis_size
    n = axis_size(axis_name)
    flat = grad.reshape(-1).astype(jnp.float32)
    size = flat.size
    pad = (-size) % n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    idx = lax.axis_index(axis_name)
    chunk = flat.size // n
    if grad_reduce == "scatter":
        # one fused reduce-scatter: member i receives sum over members
        # of slice i (tiled=False keeps the scatter dim explicit)
        g_slice = reduce_scatter(flat.reshape(n, -1), axis_name)
    elif grad_reduce == "local":
        g_slice = lax.dynamic_slice_in_dim(flat, idx * chunk, chunk)
    elif callable(grad_reduce):
        g_slice = grad_reduce(flat)
    else:
        raise MXNetError(
            f"sharded_weight_update: grad_reduce must be 'scatter', "
            f"'local', or a callable, got {grad_reduce!r}")
    p_flat = param.reshape(-1).astype(jnp.float32)
    if pad:
        p_flat = jnp.pad(p_flat, (0, pad))
    p_slice = lax.dynamic_slice_in_dim(p_flat, idx * chunk, chunk)
    new_p_slice, new_states = update_fn(p_slice, g_slice, *states)
    # cast BEFORE the gather: for bf16/f16 params an f32 gather would
    # ship the weight half of the wire at 2x the necessary bytes —
    # defeating the function's whole purpose
    new_flat = lax.all_gather(new_p_slice.astype(param.dtype),
                              axis_name, axis=0, tiled=True)
    if pad:
        new_flat = new_flat[:size]
    return new_flat.reshape(param.shape), tuple(new_states)


def sharded_update_state_init(param, n_states, axis_name_size):
    """Optimizer-state arrays for :func:`sharded_weight_update`:
    ``n_states`` zero arrays of GLOBAL shape (N, padded_size/N) — feed
    each through ``shard_map`` with ``in_specs=P(axis)`` /
    ``out_specs=P(axis)`` so every member holds its (1, chunk) slice
    (strip the leading local axis before ``update_fn``, re-add it on
    the way out: ``m2[None]``).  Per-member memory is 1/N the
    replicated state; the round-trip shape is stable across steps.
    Call OUTSIDE shard_map with the dp axis size."""
    import numpy as np

    size = 1
    for d in param.shape:
        size *= int(d)
    padded = size + ((-size) % axis_name_size)
    chunk = padded // axis_name_size
    return tuple(np.zeros((axis_name_size, chunk), "float32")
                 for _ in range(n_states))
