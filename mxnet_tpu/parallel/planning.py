"""Sharding plans: memory math for parallelism layouts BEFORE any
array exists.

The reference sized multi-GPU jobs by rule of thumb; on a TPU mesh the
layout is explicit (SURVEY.md §2.3 rebuild plan), so the plan can be
computed exactly from parameter shapes + PartitionSpecs — no 16 GB of
weights needed to learn they wouldn't fit.  Used by the Llama-3-8B
dryrun (BASELINE config #5, VERDICT r2 next #8): assert per-device
bytes fit a v5e's 16 GB HBM before ever touching a chip.
"""
from __future__ import annotations

import re

from ..base import MXNetError

__all__ = ["llama_param_rule", "sharding_plan"]

_V5E_HBM_BYTES = 16 * 1024 ** 3

_COL = ("_attn_q_weight", "_attn_k_weight", "_attn_v_weight",
        "_mlp_gate_weight", "_mlp_up_weight")
_ROW = ("_attn_o_weight", "_mlp_down_weight")
_VOCAB = ("_embed_weight", "_head_weight")


def llama_param_rule(tp_axis: str = "tp"):
    """Megatron-style tensor-parallel layout for the Llama family.

    Column-parallel: q/k/v and gate/up projections (output dim
    sharded — the following op consumes the shard locally);
    row-parallel: o and down projections (input dim sharded — XLA
    inserts the psum); vocab-sharded: embedding + untied LM head;
    norms replicated.  Returns a ``(name, shape) -> PartitionSpec``
    rule for ``DataParallelTrainer(param_sharding=...)`` /
    :func:`sharding_plan`.
    """
    from jax.sharding import PartitionSpec as P

    def rule(name, shape):
        if name.endswith(_COL) or name.endswith(_VOCAB):
            return P(tp_axis, None)
        if name.endswith(_ROW):
            return P(None, tp_axis)
        return None

    return rule


def _layer_stage(name: str, num_layers: int, num_stages: int):
    """Pipeline stage for a param: decoder layer i goes to stage
    i // ceil(L / S); embedding to the first stage, head/final norm to
    the last (the GPipe layout ``parallel.pipeline_apply`` uses)."""
    m = re.search(r"layer(\d+)_", name)
    if m:
        per = -(-num_layers // num_stages)
        return min(int(m.group(1)) // per, num_stages - 1)
    if name.endswith(_VOCAB[0]):       # embedding
        return 0
    return num_stages - 1              # head, final norm


def sharding_plan(block, mesh=None, rule=None, dtype_bytes: int = 2,
                  pp_axis: str = None, hbm_bytes: int = _V5E_HBM_BYTES):
    """Exact per-device parameter-memory plan for ``block`` on ``mesh``.

    Pure shape math over ``collect_params()`` (no initialization, no
    arrays): each param's bytes are divided by the product of the mesh
    axes its PartitionSpec uses; with ``pp_axis``, params are assigned
    to pipeline stages and the busiest stage reported.  Returns a dict:
    ``total_params``, ``per_stage_bytes`` (list, one per stage),
    ``max_device_bytes``, ``fits_hbm``, ``hbm_fraction``.

    ``rule`` may be a ``(name, shape) -> PartitionSpec`` callable OR a
    :class:`~mxnet_tpu.parallel.planner.ShardingPlan` — the planner's
    regex rules, pp axis, and mesh axes then drive the memory math
    (``mesh`` may be omitted: the plan describes it).
    """
    from .planner import ShardingPlan
    plan_obj = None
    if isinstance(rule, ShardingPlan):
        plan_obj = rule
        if pp_axis is None and plan_obj.n_stages > 1:
            pp_axis = plan_obj.pp_axis
        rule = plan_obj.partition_spec
        if mesh is None:
            mesh = dict(plan_obj.axes)   # shape math needs no devices
    elif mesh is None:
        raise MXNetError("sharding_plan needs a mesh (or a "
                         "ShardingPlan rule that describes one)")
    # accept a jax Mesh or a plain {axis: size} dict — the math only
    # reads axis sizes
    axis_sizes = dict(mesh.shape) if hasattr(mesh, "shape") else \
        {str(k): int(v) for k, v in dict(mesh).items()}
    params = {name: tuple(int(d) for d in p.shape)
              for name, p in block.collect_params().items()}
    for name, shape in params.items():
        if any(d <= 0 for d in shape):
            raise MXNetError(
                f"param {name!r} has unresolved shape {shape}; declare "
                "in_units/in_channels so the plan needs no forward")
    num_stages = int(axis_sizes[pp_axis]) if pp_axis else 1
    layer_ids = [int(m.group(1)) for n in params
                 for m in [re.search(r"layer(\d+)_", n)] if m]
    num_layers = max(layer_ids) + 1 if layer_ids else 1

    total_params = 0
    per_stage = [0] * num_stages
    for name, shape in params.items():
        n_elem = 1
        for d in shape:
            n_elem *= d
        total_params += n_elem
        shards = 1
        spec = rule(name, shape) if rule is not None else None
        if spec is not None:
            for part in spec:
                for ax in ([part] if isinstance(part, str) else
                           (part or ())):
                    shards *= int(axis_sizes[ax])
        if num_stages <= 1:
            stage = 0
        elif plan_obj is not None:
            # the plan's stage_rules override the layer-number layout
            stage = plan_obj.stage_of(name, num_layers)
        else:
            stage = _layer_stage(name, num_layers, num_stages)
        per_stage[stage] += -(-n_elem // shards) * dtype_bytes
    max_dev = max(per_stage)
    return {
        "total_params": total_params,
        "per_stage_bytes": per_stage,
        "max_device_bytes": max_dev,
        "fits_hbm": max_dev <= hbm_bytes,
        "hbm_fraction": max_dev / hbm_bytes,
    }
