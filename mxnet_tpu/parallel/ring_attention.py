"""Ring attention: sequence/context parallelism over a mesh axis.

Capability: long-context scaling the reference never had (SURVEY.md §5
"Long-context / sequence parallelism" — listed as a required first-class
capability of the rebuild).  The sequence axis is sharded over the ``sp``
mesh axis; each device holds its Q shard permanently and passes K/V
shards around the ring with ``lax.ppermute`` (XLA lowers to ICI RDMA on a
TPU torus — the same pattern as pallas_guide.md §18's ring collectives,
expressed at the collective level so it is differentiable and testable on
a CPU mesh).  Online-softmax accumulation keeps memory O(S/devices) per
chip.
"""
from __future__ import annotations

from functools import partial

import numpy as np

from ..base import MXNetError
from .mesh import current_mesh

__all__ = ["ring_attention", "ring_attention_sharded"]


def _ring_attention_local(q, k, v, axis_name, scale, causal_offset=None):
    """Per-shard body (runs inside shard_map).

    q: (B, Sq_local, H, D); k/v: (B, Sk_local, KV, D) with KV dividing H
    (grouped-query attention: each KV head serves H//KV query heads).
    Only the small KV-head tensors travel the ring — queries are grouped
    by reshape instead of materializing repeated K/V.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ._compat import axis_size
    n = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    b, sq, h, d = q.shape
    kv = k.shape[2]
    g = h // kv
    # (B, Sq, KV, G, D): query heads grouped under their KV head
    qg = q.astype(jnp.float32).reshape(b, sq, kv, g, d)

    m = jnp.full((b, sq, kv, g, 1), -jnp.inf, jnp.float32)
    # running max / sum and (B, Sq, KV, G, D) accumulator
    l = jnp.zeros_like(m)
    acc = jnp.zeros(qg.shape, jnp.float32)

    def step(i, carry):
        k_cur, v_cur, m, l, acc = carry
        # K/V block currently held came from shard (my - i) mod n
        src = (my - i) % n
        s = jnp.einsum("bqcgd,bkcd->bqcgk", qg,
                       k_cur.astype(jnp.float32)) * scale
        if causal_offset is not None:
            sk = k_cur.shape[1]
            q_pos = my * sq + jax.lax.broadcasted_iota(
                jnp.int32, (sq, sk), 0)
            k_pos = src * sk + jax.lax.broadcasted_iota(
                jnp.int32, (sq, sk), 1)
            s = jnp.where(
                (q_pos >= k_pos)[None, :, None, None, :], s, -1e30)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + p.sum(axis=-1, keepdims=True)
        acc_new = alpha * acc + jnp.einsum(
            "bqcgk,bkcd->bqcgd", p, v_cur.astype(jnp.float32))
        # rotate K/V to the next device; overlapped with next-step compute
        # by XLA's async collectives
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return k_nxt, v_nxt, m_new, l_new, acc_new

    _, _, m, l, acc = _unrolled(step, n, (k, v, m, l, acc))
    return (acc / l).reshape(q.shape).astype(q.dtype)


def _unrolled(step, n, carry):
    # static unroll: n is the mesh-axis size (small); lets XLA overlap
    # each step's ppermute with the previous step's einsum
    for i in range(n):
        carry = step(i, carry)
    return carry


# jit caches traces per function OBJECT — a fresh shard_map(partial(...))
# every call would retrace+recompile per invocation (~200x measured on an
# 8-device CPU mesh), so the jitted executable is cached per variant
_RING_EXEC_CACHE = {}


def _ring_executable(mesh, axis, scale, causal):
    import jax
    from ._compat import shard_map
    from jax.sharding import PartitionSpec as P

    key = (mesh, axis, float(scale), bool(causal))
    fn = _RING_EXEC_CACHE.get(key)
    if fn is None:
        spec = P(None, axis, None, None)
        fn = jax.jit(shard_map(
            partial(_ring_attention_local, axis_name=axis,
                    scale=float(scale),
                    causal_offset=True if causal else None),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec))
        _RING_EXEC_CACHE[key] = fn
    return fn


def _resolve_plan(plan, mesh, axis):
    """``plan`` (a ``planner.ShardingPlan``) supplies the mesh and the
    sequence axis (``plan.sp_axis``) — same convention as the pipeline
    entry points."""
    from .planner import resolve_plan_axis
    return resolve_plan_axis(plan, mesh, axis, "sp_axis")


def ring_attention(q, k, v, mesh=None, axis="sp", scale=None,
                   causal=False, plan=None):
    """SPMD ring attention over sequence-sharded jax arrays.

    q: (B, S_global, H, D); k/v: (B, S_global, KV, D) with KV dividing H
    (KV == H is plain multi-head attention), sharded or to-be-sharded
    along the sequence dim over ``axis``.  Returns (B, S_global, H, D)
    with the same sharding.  ``plan`` (a ``parallel.ShardingPlan``)
    supplies the mesh and the sequence axis (``plan.sp_axis``).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh, axis = _resolve_plan(plan, mesh, axis)
    mesh = mesh if mesh is not None else current_mesh()
    if axis not in mesh.axis_names:
        raise MXNetError(f"mesh has no axis {axis!r}")
    n = mesh.shape[axis]
    if q.shape[1] % n:
        raise MXNetError(
            f"sequence length {q.shape[1]} not divisible by mesh axis "
            f"{axis!r} size {n}")
    if q.shape[2] % k.shape[2]:
        raise MXNetError(
            f"query heads {q.shape[2]} not a multiple of KV heads "
            f"{k.shape[2]}")
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])

    sharding = NamedSharding(mesh, P(None, axis, None, None))
    q = jax.device_put(q, sharding)
    k = jax.device_put(k, sharding)
    v = jax.device_put(v, sharding)
    return _ring_executable(mesh, axis, scale, causal)(q, k, v)


_SHARDED_OPDEF_CACHE = {}
_OPDEF_SEQ = __import__("itertools").count()


def ring_attention_sharded(q_nd, k_nd, v_nd, mesh=None, axis="sp",
                           scale=None, causal=False, plan=None):
    """NDArray wrapper around :func:`ring_attention` — on the autograd
    tape, so training through the ring path gets real gradients.

    When the inputs live on ONE device (eager model forward mixing
    single-device weights with the SP mesh), the output is brought back
    to that device — only the attention itself (the quadratic part)
    runs sequence-sharded.  Fully-sharded callers keep the sharding.

    Not usable inside a single-device CachedOp trace (hybridize): the
    shard_map needs the mesh's devices, which a one-device jit cannot
    provide — run eagerly, or inside a mesh-jitted SPMD step.
    """
    import jax
    from ..base import MXNetError
    from ..gluon.block import _is_tracing
    from ..ndarray.ndarray import invoke
    from ..ops.registry import OpDef

    if _is_tracing():
        raise MXNetError(
            "ring attention cannot run inside a single-device "
            "hybridize/CachedOp trace; call the block unhybridized or "
            "run it inside a mesh-jitted SPMD step")

    mesh, axis = _resolve_plan(plan, mesh, axis)
    mesh = mesh if mesh is not None else current_mesh()
    try:
        devs = q_nd._data.sharding.device_set
        restore = (next(iter(devs)) if len(devs) == 1 else None)
    except Exception:
        restore = None

    d = q_nd.shape[-1]
    s = float(scale) if scale is not None else 1.0 / float(np.sqrt(d))
    key = (mesh, axis, s, bool(causal), restore)
    op = _SHARDED_OPDEF_CACHE.get(key)
    if op is None:
        def fcompute(q, k, v):
            out = ring_attention(q, k, v, mesh=mesh, axis=axis,
                                 scale=s, causal=causal)
            if restore is not None:
                out = jax.device_put(out, restore)
            return out

        # placement (device_put to the mesh, restore to one device)
        # happens inside fcompute — an outer single-device jit would
        # reject the cross-device transfers
        fcompute._mxtpu_no_jit = True
        # engine.get_compiled caches executables by (op.name, attrs), so
        # the name must be unique per (mesh, axis, scale, causal, restore)
        # variant — a shared name would silently reuse the first-compiled
        # closure for every later variant
        op = OpDef("_ring_attention_%d" % next(_OPDEF_SEQ),
                   fcompute, 3, 1, (), False, None)
        _SHARDED_OPDEF_CACHE[key] = op
    return invoke(op, [q_nd, k_nd, v_nd])
