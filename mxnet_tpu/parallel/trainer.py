"""One-jit SPMD training: the TPU-native fast path.

The reference's fastest configuration was ``Module`` + ``kvstore='nccl'``:
per-GPU executors, NCCL allreduce, Python-driven optimizer ops.  The
TPU-native equivalent collapses the iteration into compiled XLA programs
over the device mesh (SURVEY.md §2.3 "Rebuild plan" column):

* batch arrives sharded along ``dp``;
* params/optimizer state are replicated (or sharded by a TP rule);
* the loss is a mean over the *global* batch, so XLA inserts the gradient
  all-reduce over ICI automatically — no kvstore round-trip, no per-op
  dispatch inside a step;
* the optimizer applies as ONE fused multi-tensor program (the reference's
  ``multi_sgd_update`` idea, generalized), with per-step scalars (lr
  schedule, Adam bias correction) riding as dynamic 0-d inputs so nothing
  recompiles between steps.

``DataParallelTrainer`` reuses the Gluon block/optimizer objects
unchanged: the block is traced (CachedOp-style buffer swap); BatchNorm-
style aux-state mutation is carried out of the jit as explicit outputs
(`has_aux`), reproducing the imperative path's observable updates.
"""
from __future__ import annotations

import math
from typing import Callable, List, Optional

import numpy as np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from ..ops.registry import get_op
from .mesh import current_mesh

__all__ = ["DataParallelTrainer"]

# distinct "no override" sentinel for _sharding_tuples(rule=): None
# must stay expressible as "explicitly replicate" (a rule-free target
# plan in a live resize)
_RULE_UNSET = object()


def _flatten(tree, out):
    if tree is None:
        return
    if isinstance(tree, NDArray):
        out.append(tree)
        return
    if isinstance(tree, (list, tuple)):
        for t in tree:
            _flatten(t, out)
        return
    raise MXNetError(f"unsupported optimizer state leaf: {type(tree)}")


class _FusedRule:
    """How to apply one optimizer class as a fused on-chip update.

    ``scalars(opt, i, t)`` returns the per-step dynamic scalars
    (pre-computed in Python, mirroring ``Optimizer.update``'s host math —
    e.g. Adam's bias-corrected lr); ``apply(opt, w, g, states, *scalars)``
    runs the registered fused op's pure fcompute and returns
    ``(new_w, new_states_tuple)``.

    ``pointwise`` declares the rule elementwise in the FLAT parameter —
    the ZeRO eligibility bit (docs/zero.md): a pointwise rule applied to
    a 1/N slice computes exactly the replicated update's values, while a
    rule with per-tensor statistics (LAMB's trust ratio over ||w||)
    would silently compute them per SLICE.  Required explicitly per rule
    so adding one forces the decision here, not in a distant list.
    """

    def __init__(self, n_states, scalars, apply, *, pointwise):
        self.n_states = n_states
        self.scalars = scalars
        self.apply = apply
        self.pointwise = bool(pointwise)


def _sgd_scalars(o, i, t):
    return (o._get_lr(i), o._get_wd(i))


def _adam_corrected_lr(o, i, t):
    """Bias-corrected learning rate (shared by Adam and AdamW)."""
    return (o._get_lr(i) * math.sqrt(1.0 - o.beta2 ** t)
            / (1.0 - o.beta1 ** t))


_FUSED_RULES = {
    "SGD": _FusedRule(
        1, _sgd_scalars,
        lambda o, w, g, s, lr, wd: (
            (get_op("sgd_update").fcompute(
                w, g, lr, wd, rescale_grad=o.rescale_grad,
                clip_gradient=o._clip() or -1.0), ())
            if not s else
            get_op("sgd_mom_update").fcompute(
                w, g, s[0], lr, wd, momentum=o.momentum,
                rescale_grad=o.rescale_grad,
                clip_gradient=o._clip() or -1.0)), pointwise=True),
    "NAG": _FusedRule(
        1, _sgd_scalars,
        lambda o, w, g, s, lr, wd: get_op("nag_mom_update").fcompute(
            w, g, s[0], lr, wd, momentum=o.momentum,
            rescale_grad=o.rescale_grad,
            clip_gradient=o._clip() or -1.0), pointwise=True),
    "Adam": _FusedRule(
        2,
        lambda o, i, t: (_adam_corrected_lr(o, i, t), o._get_wd(i)),
        lambda o, w, g, s, lr, wd: get_op("adam_update").fcompute(
            w, g, s[0], s[1], lr, wd, beta1=o.beta1, beta2=o.beta2,
            epsilon=o.epsilon, rescale_grad=o.rescale_grad,
            clip_gradient=o._clip() or -1.0), pointwise=True),
    "RMSProp": _FusedRule(
        1, _sgd_scalars,
        lambda o, w, g, s, lr, wd: get_op("rmsprop_update").fcompute(
            w, g, s[0], lr, wd, gamma1=o.gamma1, epsilon=o.epsilon,
            rescale_grad=o.rescale_grad,
            clip_gradient=o._clip() or -1.0), pointwise=True),
    "AdamW": _FusedRule(
        2,
        lambda o, i, t: (_adam_corrected_lr(o, i, t), 1.0,
                         o._get_wd(i)),
        lambda o, w, g, s, lr, eta, wd: get_op("adamw_update").fcompute(
            w, g, s[0], s[1], lr, eta, wd, beta1=o.beta1, beta2=o.beta2,
            epsilon=o.epsilon, rescale_grad=o.rescale_grad,
            clip_gradient=o._clip() or -1.0), pointwise=True),
    "AdaGrad": _FusedRule(
        1, _sgd_scalars,
        lambda o, w, g, s, lr, wd: get_op("adagrad_update").fcompute(
            w, g, s[0], lr, wd, epsilon=o.float_stable_eps,
            rescale_grad=o.rescale_grad,
            clip_gradient=o._clip() or -1.0), pointwise=True),
}


def _apply_rule(rule, opt, tr_count, n_scalars, get_param, tstate_vals,
                grads, scalar_vals):
    """Apply the fused optimizer rule to every trainable param (shared
    by the two-phase update program and the fully-fused step)."""
    new_params, new_states = [], []
    for j in range(tr_count):
        scal = tuple(scalar_vals[j * n_scalars + k]
                     for k in range(n_scalars))
        st = tstate_vals[j]
        res = rule.apply(opt, get_param(j), grads[j], st, *scal)
        if isinstance(res, tuple) and isinstance(res[1], tuple):
            w, new_st = res
        else:
            w, new_st = res[0], tuple(res[1:])
        new_params.append(w)
        new_states.append(new_st if new_st else st)
    return tuple(new_params), tuple(new_states)


class DataParallelTrainer:
    """SPMD data-parallel trainer over a device mesh.

    Args:
      block: an initialized Gluon (Hybrid)Block.
      loss_fn: callable ``(pred, label) -> NDArray`` (e.g. a gluon loss).
      optimizer: name or ``mx.optimizer.Optimizer`` instance.
      optimizer_params: kwargs when ``optimizer`` is a name.
      mesh: a ``jax.sharding.Mesh``; defaults to ``current_mesh()``.
      dp_axis: mesh axis to shard the batch over.
      param_sharding: optional rule ``(param_name, shape) ->
        jax.sharding.PartitionSpec`` for tensor-parallel param layouts;
        default replicates every param (pure DP).
      plan: a :class:`~mxnet_tpu.parallel.planner.ShardingPlan` — the
        declarative alternative to ``mesh``/``dp_axis``/
        ``param_sharding`` (docs/parallelism.md, "The sharding
        planner"): the plan's named axes build the mesh, its regex
        rules become the param layout, and its ``zero_stage`` (when
        set) overrides ``MXTPU_ZERO_STAGE``.  Defaults to the plan
        ``MXTPU_SHARDING_PLAN`` points at.  Mutually exclusive with
        ``param_sharding``; an explicit ``mesh`` must match the plan's
        axes.
    """

    def __init__(self, block, loss_fn: Callable, optimizer,
                 optimizer_params=None, mesh=None, dp_axis: str = "dp",
                 param_sharding: Optional[Callable] = None,
                 fuse_step: bool = False, compression=None, plan=None):
        from .. import optimizer as opt
        from . import planner as _planner

        self.block = block
        self.loss_fn = loss_fn
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params
            self.optimizer = optimizer
        else:
            self.optimizer = opt.create(optimizer,
                                        **(optimizer_params or {}))
        # the unified sharding planner (ROADMAP item 1): ONE plan
        # object drives the mesh, the param layout, the ZeRO stage and
        # (downstream) pipeline/serving axes — the env entry point
        # makes a plan file the process-wide source of truth.  The env
        # plan is AMBIENT: explicit legacy layout args win over it (a
        # param_sharding rule skips adoption entirely; a mesh whose
        # axes disagree warns and keeps the legacy path), so setting
        # MXTPU_SHARDING_PLAN can never brick pre-planner call sites.
        # An EXPLICIT plan= keeps the strict conflict rejects below.
        if plan is None and param_sharding is None:
            env_plan = _planner.plan_from_env()
            mesh_conflict = env_plan is not None and \
                mesh is not None and \
                {str(k): int(v) for k, v in mesh.shape.items()} \
                != dict(env_plan.axes)
            axis_conflict = env_plan is not None and \
                dp_axis not in ("dp", env_plan.dp_axis)
            if mesh_conflict or axis_conflict:
                import warnings
                what = "mesh axes" if mesh_conflict else "dp_axis"
                warnings.warn(
                    f"MXTPU_SHARDING_PLAN disagrees with this "
                    f"trainer's explicit {what}; ignoring the env "
                    "plan (explicit args win)", stacklevel=2)
            else:
                plan = env_plan
        if plan is not None:
            if not isinstance(plan, _planner.ShardingPlan):
                raise MXNetError(
                    f"plan= must be a parallel.ShardingPlan, got "
                    f"{type(plan).__name__}")
            if param_sharding is not None:
                raise MXNetError(
                    "pass plan= OR param_sharding=, not both — the "
                    "plan's rules ARE the param layout")
            if dp_axis not in ("dp", plan.dp_axis):
                raise MXNetError(
                    f"dp_axis {dp_axis!r} conflicts with the plan's "
                    f"dp_axis {plan.dp_axis!r}")
            dp_axis = plan.dp_axis
            if mesh is None:
                mesh = plan.build_mesh()
            else:
                mesh_axes = {str(k): int(v)
                             for k, v in mesh.shape.items()}
                if mesh_axes != dict(plan.axes):
                    raise MXNetError(
                        f"mesh axes {mesh_axes} do not match the "
                        f"plan's {dict(plan.axes)}")
            param_sharding = plan.param_rule()
        self.plan = plan
        self.mesh = mesh if mesh is not None else current_mesh()
        self.dp_axis = dp_axis
        self._param_sharding = param_sharding
        self._params = None
        self._fwd_bwd = None
        self._fused_update = None
        self._full_step = None
        self._full_donate = (1,)
        # fuse_step=True compiles forward+backward+optimizer into ONE
        # program (optimizer states donated), removing the gradient
        # round-trip through HBM between the two phases; requires a
        # fused optimizer rule
        self._fuse_step = fuse_step
        # set when a fused step failed after its donated optimizer
        # state was handed to the executable (see _step_impl)
        self._donation_poisoned = None
        # one-shot callback fired at the end of the first successful
        # step after a live resize swap (elastic.resize finalizes the
        # pre-warm-contract accounting there — MXL503)
        self._post_resize_probe = None
        # id(NDArray) -> (weakref, source buffer, placed buffer,
        # requested sharding);
        # pruned to the CURRENT step's inputs each step, so at most
        # n_args+1 placements are ever pinned (id keys because NDArray
        # __eq__ is elementwise — a WeakKeyDictionary lookup would
        # crash in bool())
        self._placed = {}
        self._full_fn = None
        self._multi_step_cache = {}
        self._mutated_idx: List[int] = []
        # persistent-compile-cache plumbing (docs/compile_cache.md):
        # the fused step dispatches through an EXPLICIT AOT executable
        # so it can be serialized across restarts; the unjitted step
        # bodies are kept for the abstract re-trace a persist hit needs
        # (mutated_idx discovery), and warm-start manifests pin the
        # save-time identity + record the mesh/sharding layout
        self._full_exec = None
        self._multi_exec = {}
        self._multi_fns = {}
        self._trace_seen = [False]
        self._persist_pin: Optional[str] = None
        self._var_avals = {}
        self.warm_started = False
        # training-health plane (telemetry.health): spec of the extra
        # in-graph stats vector the fused step returns (None = off);
        # _health_built_sig records the config the current programs
        # bake so an env flip rebuilds them (with attribution) instead
        # of mis-unpacking outputs; health_manager arms the rollback
        # action
        self._health_spec = None
        self._health_built_sig = None
        self._health_count = 0
        self.health_manager = None
        self._rule = _FUSED_RULES.get(type(self.optimizer).__name__)
        if fuse_step and self._rule is None:
            import warnings
            warnings.warn(
                f"fuse_step=True requested but optimizer "
                f"{type(self.optimizer).__name__} has no fused rule; "
                "falling back to the two-phase step", stacklevel=2)
        # gradient compression over the dp wire (reference
        # src/kvstore/gradient_compression.cc; here it runs INSIDE the
        # fused SPMD step): {'type': 'int8'} for stateless int8-wire
        # quantized allreduce, {'type': '2bit', 'threshold': t} for
        # ternary codes with per-device error-feedback residuals
        self._compression_cfg = None
        self._residual_vals = None
        if compression is not None:
            cfg = dict(compression)
            ctype = cfg.get("type")
            if ctype not in ("int8", "2bit"):
                raise MXNetError(
                    f"compression type must be 'int8' or '2bit', got "
                    f"{ctype!r}")
            allowed = {"type", "threshold"} if ctype == "2bit" \
                else {"type"}
            unknown = set(cfg) - allowed
            if unknown:
                raise MXNetError(
                    f"unknown compression option(s) {sorted(unknown)} "
                    f"for type {ctype!r} (allowed: {sorted(allowed)}) "
                    "— a typo here would otherwise silently use "
                    "defaults")
            if ctype == "2bit" and \
                    not float(cfg.get("threshold", 0.5)) > 0:
                raise MXNetError("compression threshold must be "
                                 "positive")
            if param_sharding is not None:
                raise MXNetError(
                    "gradient compression is a data-parallel wire "
                    "optimization; it cannot combine with a "
                    "param_sharding (tensor-parallel) rule")
            if not fuse_step or self._rule is None:
                raise MXNetError(
                    "gradient compression requires fuse_step=True with "
                    "a fused optimizer rule (the compressed exchange "
                    "lives inside the single SPMD step program)")
            self._compression_cfg = cfg
        # ZeRO-1/2 sharded weight update (docs/zero.md, arXiv
        # 2004.13336): latched at construction — the stage decides the
        # PHYSICAL optimizer-state layout, which cannot flip under a
        # live trainer the way a health sampling knob can.  Ineligible
        # trainers warn and run stage 0; the replicated layout then
        # trips the MXL310 runtime rule.
        from . import zero as _zero
        self._zero_stage = 0
        # the plan's zero_stage (when set) IS the stage — one plan
        # object decides the (dp, chunk) layout; None defers to the env
        if self.plan is not None and self.plan.zero_stage is not None:
            requested = int(self.plan.zero_stage)
        else:
            requested = _zero.stage_from_env()
        if requested and int(self.mesh.shape.get(self.dp_axis, 1)) > 1:
            reason = _zero.eligibility(self)
            if reason is None:
                self._zero_stage = requested
            else:
                import warnings
                warnings.warn(
                    f"MXTPU_ZERO_STAGE={requested} requested but this "
                    f"trainer cannot shard its update ({reason}); "
                    "running stage 0 — optimizer state stays "
                    "replicated", stacklevel=2)
        # the per-device step body backing the bulked (scan) builder
        # when ZeRO is on; self._full_fn then holds the shard_map-
        # wrapped single-step twin (traceable at GLOBAL avals, which
        # the persist tier's eval_shape re-trace needs)
        self._zero_body = None

    # -- lazy setup -------------------------------------------------------
    def _setup(self, args):
        from .. import autograd
        params = list(self.block.collect_params().values())
        if any(p._deferred_init for p in params):
            with autograd.pause():
                self.block._call_unhybridized(*args)
        self._finish_setup(params)

    def _finish_setup(self, params):
        from . import zero as _zero
        self._params = params
        self._trainable = [p.grad_req != "null" for p in params]
        self._tr_idx = [i for i, t in enumerate(self._trainable) if t]
        if self._zero_stage:
            # sharded layout (docs/zero.md): each trainable param's
            # state is a tuple of (n_dp, chunk) f32 leaves placed
            # P(dp) — every member holds 1/N of Adam's m/v instead of
            # a full replica; leaf COUNT still comes from the
            # optimizer's own create_state
            self._states = [
                _zero.create_sharded_states(
                    self.optimizer, i, p.data(), self.mesh,
                    self.dp_axis)
                if self._trainable[i] else None
                for i, p in enumerate(params)]
        else:
            self._states = [
                self.optimizer.create_state(i, p.data())
                if self._trainable[i] else None
                for i, p in enumerate(params)]
        self._shard_params()
        # the observatory's optimizer-state ledger: per-leaf global vs
        # per-device bytes, sharded/replicated split — the evidence
        # the ~dp x ZeRO drop is measured against, and the MXL310
        # input (env says shard, layout says replicated)
        from .. import telemetry
        telemetry.memory.note_opt_state(
            f"spmd:{self.block.name}", self._opt_state_leaves(),
            mesh=self.mesh, dp_axis=self.dp_axis,
            zero_stage=self._zero_stage)
        # the planner registry (MXL313 coverage audit + mxplan): a
        # plan-driven trainer's resolved param tree is auditable for
        # uncovered params / shadowed rules / replicated big tensors
        if self.plan is not None:
            from . import planner as _planner
            _planner.note_plan(
                f"spmd:{self.block.name}", self.plan,
                [(p.name, p.data().shape) for p in params])

    def _param_spec(self, name, shape):
        """The trainer's sharding rule (plan-derived or callable) for
        one param — the single consultation point behind
        ``_shard_params``/``_sharding_tuples``/``_elastic_restore``."""
        if self._param_sharding is None:
            return None
        return self._param_sharding(name, shape)

    def _opt_state_leaves(self):
        """``[(label, jax array), ...]`` over every optimizer-state
        leaf, labelled by owning param (the census/MXL310 input)."""
        out = []
        for i in self._tr_idx:
            leaves: List[NDArray] = []
            _flatten(self._states[i], leaves)
            for j, leaf in enumerate(leaves):
                out.append((f"{self._params[i].name}:{j}", leaf._data))
        return out

    def _ensure_setup_for_restore(self):
        """Checkpoint restore may land BEFORE the first batch (a fresh
        process resuming on a possibly different mesh): initialize the
        param/state plumbing without a batch.  Deferred shapes cannot
        be resolved batch-free — the caller must build the net with
        explicit in_units/in_channels (or run one step first)."""
        if self._params is not None:
            return
        params = list(self.block.collect_params().values())
        if any(p._deferred_init for p in params):
            raise MXNetError(
                "cannot restore a checkpoint into a trainer whose "
                "parameter shapes are still deferred; build the block "
                "with explicit input sizes or run one step before "
                "restoring")
        self._finish_setup(params)

    def _integrity_sig(self):
        """The integrity sentry's trace signature for THIS trainer
        (``elastic.integrity``): ``None`` on a <=1-dp mesh or with the
        plane off — the program is then byte-identical to a
        pre-integrity build.  Grad fingerprint rows are dropped under
        ZeRO stage 2, whose replicated gradient never materializes
        (docs/zero.md)."""
        from ..elastic import integrity as _integrity
        return _integrity.trace_signature(
            self.mesh, self.dp_axis,
            grad_rows=self._zero_stage != 2)

    def _build_integrity_spec(self):
        from ..elastic import integrity as _integrity
        return _integrity.build_spec(self.mesh, self.dp_axis,
                                     grad_rows=self._zero_stage != 2)

    def _integrity_struct_sig(self):
        from ..elastic import integrity as _integrity
        return _integrity.struct_signature(
            grad_rows=self._zero_stage != 2)

    def _refresh_health(self):
        """(Re)build the health spec when the ``MXTPU_HEALTH*`` /
        ``MXTPU_INTEGRITY*`` config the compiled programs bake drifted
        (the integrity sentry's fingerprint rows ride the health
        vector, and arming a corruption drill adds the ctl input).  A
        flip after programs were built evicts them (they return a
        different output arity) with an attributed ``retrace`` event —
        the same correctness-over-cache-warmth rule as
        ``CompiledStep._check_sig``."""
        from .. import telemetry
        hcfg = telemetry.health.trace_signature()
        icfg = self._integrity_sig() if hcfg is not None else None
        # bare health tuple when integrity is off, so every
        # pre-integrity built-signature (and the single-device paths)
        # compares unchanged
        cfg = hcfg if icfg is None else (hcfg, icfg)
        if cfg == self._health_built_sig:
            return
        spec = telemetry.health.build_spec(
            self.block.name,
            [self._params[i].name for i in self._tr_idx],
            integrity=self._build_integrity_spec()) \
            if hcfg is not None else None
        if self._health_built_sig != cfg and (
                self._full_fn is not None or
                self._full_step is not None):
            if telemetry.enabled():
                def _lbl(c):
                    if c is None:
                        return "off"
                    h = c[0] if isinstance(c[0], tuple) else c
                    lbl = "on(skip-gate)" if h[2] else "on"
                    if isinstance(c[0], tuple) and c[1] is not None:
                        lbl += "+integrity" + (
                            "(inject)" if c[1][4] else "")
                    return lbl
                telemetry.counter(
                    "mxtpu_retraces_total",
                    "cache misses attributable to a changed "
                    "attr/shape/dtype").inc()
                telemetry.record_event(
                    "retrace", op="spmd_full_step", cause="attrs",
                    changed={"health": [
                        _lbl(self._health_built_sig), _lbl(cfg)]},
                    source="spmd_trainer")
            self._full_step = None
            self._full_fn = None
            self._zero_body = None
            self._full_exec = None
            self._multi_step_cache.clear()
            self._multi_fns.clear()
            self._multi_exec.clear()
            # recorded manifest rows bake the old call signature (the
            # due-flag "extra" entry) — stale rows would make every
            # warm start in the new config fail over to cold compile
            self._var_avals.clear()
        self._health_spec = spec
        self._health_built_sig = cfg

    def _shard_params(self):
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..elastic import reshard as _reshard
        from . import planner as _planner

        repl = NamedSharding(self.mesh, P())
        holders: List[NDArray] = [p.data() for p in self._params]
        # THE shared resolution path (planner.resolve_shardings):
        # _sharding_tuples and _elastic_restore derive the same
        # layouts through the same call, so placement and pinned
        # program shardings can never disagree
        targets = list(_planner.resolve_shardings(
            self.mesh,
            [(p.name, p.data().shape) for p in self._params],
            self._param_sharding))
        flat: List[NDArray] = []
        _flatten(self._states, flat)
        holders.extend(flat)
        # ZeRO keeps optimizer-state leaves sharded on their leading
        # dp row — re-replicating them here would silently undo the
        # whole memory saving (and trip MXL310)
        state_target = _planner.zero_state_sharding(
            self.mesh, self.dp_axis) if self._zero_stage else repl
        targets.extend(state_target for _ in flat)
        # live -> live layout move (elastic.reshard, arXiv:2112.01075):
        # one compiled identity program when source and target cover
        # the same device set, the runtime transfer engine otherwise
        moved = _reshard.redistribute([h._data for h in holders],
                                      targets)
        for h, a in zip(holders, moved):
            h._set_data(a)
        # the observatory's MXL309 input: the final param layout on
        # this mesh (a big tensor left fully replicated across a >1-
        # device mesh is the misuse the sharding planner must prevent)
        from .. import telemetry
        telemetry.memory.note_param_tree(
            f"spmd:{self.block.name}", self._params, mesh=self.mesh,
            dp_axis=self.dp_axis)

    # -- phase A: fused forward+backward ---------------------------------
    def _build_fwd_bwd(self, args, label):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        from .. import random as _rnd
        from ..gluon import block as block_mod

        block, loss_fn = self.block, self.loss_fn
        params = self._params
        n_args = len(args)
        ctx = args[0].context
        param_nds = [p.data() for p in params]
        tr_idx = self._tr_idx
        mutated_idx: List[int] = []
        trace_seen = self._trace_seen

        def traced(param_vals, input_vals, label_val, key_raw):
            trace_seen[0] = True     # body runs only under a trace
            key_counter = [0]

            def key_provider(_ctx):
                k = jax.random.fold_in(
                    jax.random.wrap_key_data(key_raw), key_counter[0])
                key_counter[0] += 1
                return NDArray(jax.random.key_data(k), ctx=ctx)

            _rnd._push_key_provider(key_provider)
            try:
                # tracing_scope restores every param buffer+version on
                # exit; loss_of still swaps buffers per-invocation
                with block_mod.tracing_scope(param_nds):
                    # differentiate only trainable params — frozen
                    # weights / BN running stats ride along as
                    # closed-over constants, so no dead gradient
                    # buffers are materialized
                    tr_set = set(tr_idx)

                    def loss_of(tvals):
                        vers = []
                        for j, i in enumerate(tr_idx):
                            param_nds[i]._buf = tvals[j]
                        for i, r in enumerate(param_nds):
                            if i not in tr_set:
                                r._buf = param_vals[i]
                            vers.append(r._version)
                        shells = [NDArray(v, ctx=ctx)
                                  for v in input_vals]
                        out = block._call_unhybridized(*shells)
                        l = loss_fn(out, NDArray(label_val, ctx=ctx))
                        mutated_idx.clear()
                        mutated_idx.extend(
                            i for i, (r, v0) in enumerate(
                                zip(param_nds, vers))
                            if r._version != v0)
                        aux = tuple(param_nds[i]._buf
                                    for i in mutated_idx)
                        return jnp.mean(l._data), aux

                    tvals = tuple(param_vals[i] for i in tr_idx)
                    (loss, aux), grads = jax.value_and_grad(
                        loss_of, has_aux=True)(tvals)
            finally:
                _rnd._pop_key_provider()
            return loss, grads, aux

        batch = NamedSharding(self.mesh, P(self.dp_axis))
        repl = NamedSharding(self.mesh, P())
        param_shardings = tuple(p.data()._data.sharding for p in params)
        self._traced_fn = traced          # reused by the fused step
        self._n_args = n_args
        self._fwd_bwd = jax.jit(
            traced,
            in_shardings=(param_shardings, (batch,) * n_args, batch, repl))
        self._mutated_idx = mutated_idx

    # -- phase B: fused multi-tensor optimizer ---------------------------
    def _build_fused_update(self):
        """One multi-tensor program updating every trainable param
        (reference ``multi_sgd_update`` generalized); all lists aligned
        with ``self._tr_idx``."""
        import jax

        rule = self._rule
        opt = self.optimizer
        n_scalars = len(rule.scalars(opt, 0, 1))

        n_tr = len(self._tr_idx)

        def update_all(tparam_vals, tstate_vals, grad_vals, scalar_vals):
            return _apply_rule(rule, opt, n_tr, n_scalars,
                               lambda j: tparam_vals[j], tstate_vals,
                               grad_vals, scalar_vals)

        # pin output shardings to the input param/state layouts so a
        # TP-sharded forward can't silently re-shard weights between steps
        param_shardings = tuple(
            self._params[i].data()._data.sharding for i in self._tr_idx)
        state_shardings = tuple(
            tuple(v.sharding for v in vals) for vals in self._state_vals())
        self._fused_update = jax.jit(
            update_all, donate_argnums=(0, 1),
            out_shardings=(param_shardings, state_shardings))

    def _state_vals(self):
        out = []
        for i in self._tr_idx:
            s = self._states[i]
            if s is None:
                out.append(())
            elif isinstance(s, tuple):
                out.append(tuple(x._data for x in s))
            else:
                out.append((s._data,))
        return tuple(out)

    def _write_states(self, new_state_vals):
        for i, vals in zip(self._tr_idx, new_state_vals):
            s = self._states[i]
            if s is None or not vals:
                continue
            if isinstance(s, tuple):
                for x, v in zip(s, vals):
                    x._set_data(v)
            else:
                s._set_data(vals[0])

    def _build_full_step(self):
        """ONE program: loss/grads + the multi-tensor optimizer update,
        with optimizer states donated (their buffers are dead the
        moment the new states exist)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        rule = self._rule
        opt = self.optimizer
        n_scalars = len(rule.scalars(opt, 0, 1))
        tr_idx = self._tr_idx
        traced = self._traced_fn
        hspec = self._health_spec
        ispec = hspec.integrity if hspec is not None else None
        mesh = self.mesh
        dp_axis = self.dp_axis
        mutated_idx = self._mutated_idx

        def full(param_vals, tstate_vals, scalar_vals, input_vals,
                 label_val, key_raw, due=None, ictl=None):
            loss, grads, aux = traced(param_vals, input_vals, label_val,
                                      key_raw)
            old_tr = tuple(param_vals[i] for i in tr_idx)
            irows = None
            if ispec is not None:
                # the integrity sentry (elastic.integrity): per-dp-
                # replica fingerprints of the input params + the
                # gradients, computed by one inner shard_map under the
                # same `due` sampling gate.  With a corruption drill
                # armed the block also XORs the targeted device's
                # gradient BEFORE the update reads it — the corruption
                # enters the real dataflow and the same block's grad
                # rows detect it.
                from ..elastic import integrity as _integrity
                grads, irows = _integrity.jit_block(
                    ispec, mesh, dp_axis, old_tr, grads, due=due,
                    ictl=ictl)
            new_params, new_states = _apply_rule(
                rule, opt, len(tr_idx), n_scalars,
                lambda j: param_vals[tr_idx[j]], tstate_vals, grads,
                scalar_vals)
            if hspec is None:
                return loss, new_params, new_states, aux
            # in-graph health stats (telemetry.health): the gradients
            # here are already GLOBAL (the loss is a global-batch
            # mean), so grad_norm is the cross-replica norm for free;
            # `due` gates the reductions to sampled steps
            from ..telemetry import health as _health
            import jax.numpy as jnp
            hvec = _health.compute(hspec, loss, old_tr, grads,
                                   new_params, due=due)
            if irows is not None:
                hvec = jnp.concatenate([hvec, irows])
            if hspec.skip:
                new_params, new_states, aux = _health.gate_update(
                    hvec, new_params, old_tr, new_states, tstate_vals,
                    aux, tuple(param_vals[i] for i in mutated_idx))
            return loss, new_params, new_states, aux, hvec

        self._full_fn = full          # unjitted: reused by step_multi
        batch = NamedSharding(self.mesh, P(self.dp_axis))
        repl = NamedSharding(self.mesh, P())
        param_shardings, state_shardings = self._sharding_tuples()
        tr_param_shardings = tuple(param_shardings[i] for i in tr_idx)
        # out shardings pinned for the same reason as the two-phase
        # update: a TP rule must not let XLA silently re-shard weights
        # between steps (and donation aliasing needs stable layouts)
        out_shardings = (None, tr_param_shardings, state_shardings,
                         None)
        in_shardings = (param_shardings, state_shardings, None,
                        (batch,) * self._n_args, batch, repl)
        if hspec is not None:
            out_shardings = out_shardings + (None,)
            in_shardings = in_shardings + (None,)   # the due flag
            if ispec is not None and ispec.inject:
                in_shardings = in_shardings + (None,)   # the ctl row
        self._full_step = jax.jit(
            full,
            in_shardings=in_shardings,
            out_shardings=out_shardings,
            donate_argnums=(1,))

    def _build_full_step_compressed(self):
        """The fused step with an EXPLICIT gradient wire: shard_map over
        the mesh, per-device forward/backward on the local batch shard,
        then a quantized collective exchanges the gradients (int8 lanes
        on the wire instead of fp32 — reference
        ``src/kvstore/gradient_compression.cc``), and every device
        applies the identical optimizer update.

        The uncompressed trainer leaves the gradient all-reduce implicit
        (XLA derives it from the global-batch mean); compression needs
        the collective spelled out, which is exactly what shard_map is
        for.  Per-device dropout keys are decorrelated by folding in the
        dp axis index; BatchNorm-style aux mutations are pmean'd across
        replicas (cross-replica averaging, as SyncBatchNorm does)."""
        import jax
        import jax.numpy as jnp
        import jax.lax as lax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ._compat import shard_map
        from .collectives import quantized_psum, twobit_psum

        rule = self._rule
        opt = self.optimizer
        n_scalars = len(rule.scalars(opt, 0, 1))
        tr_idx = self._tr_idx
        traced = self._traced_fn
        cfg = self._compression_cfg
        ctype = cfg["type"]
        threshold = float(cfg.get("threshold", 0.5))
        axis = self.dp_axis
        n_dp = int(self.mesh.shape[axis])
        use_residual = ctype == "2bit"
        hspec = self._health_spec
        ispec = hspec.integrity if hspec is not None else None
        other_axes = tuple(a for a in self.mesh.axis_names
                           if a != axis)
        mutated_idx = self._mutated_idx

        def full(param_vals, tstate_vals, scalar_vals, input_vals,
                 label_val, key_raw, residual_vals, due=None,
                 ictl=None):
            dev_key = jax.random.key_data(jax.random.fold_in(
                jax.random.wrap_key_data(key_raw),
                lax.axis_index(axis)))
            loss, grads, aux = traced(param_vals, input_vals,
                                      label_val, dev_key)
            red_grads, new_residuals = [], []
            for j, g in enumerate(grads):
                if ctype == "int8":
                    red_grads.append(quantized_psum(g, axis) / n_dp)
                else:
                    r = residual_vals[j].reshape(g.shape)
                    total, new_r = twobit_psum(
                        g, axis, threshold=threshold, residual=r)
                    red_grads.append(total / n_dp)
                    new_residuals.append(
                        new_r.reshape((1,) + g.shape))
            old_tr = tuple(param_vals[i] for i in tr_idx)
            irows = None
            if ispec is not None:
                from ..elastic import integrity as _integrity
                # a corrupt_wire/corrupt_grad drill flips a bit in the
                # targeted device's POST-exchange gradient — exactly
                # the payload a corrupt collective link delivers; the
                # fingerprint rows below see it with attribution
                red_grads = list(_integrity.maybe_corrupt(
                    ispec, ictl, tuple(red_grads), axis))
                irows = _integrity.body_rows(
                    ispec, axis, other_axes, old_tr,
                    tuple(red_grads), due=due)
            new_params, new_states = _apply_rule(
                rule, opt, len(tr_idx), n_scalars,
                lambda j: param_vals[tr_idx[j]], tstate_vals,
                tuple(red_grads), scalar_vals)
            loss = lax.pmean(loss, axis)
            aux = tuple(lax.pmean(a, axis) for a in aux)
            new_residuals = tuple(new_residuals)
            if hspec is None:
                return loss, new_params, new_states, aux, \
                    new_residuals
            # health over the REDUCED (post-exchange) gradients — the
            # values the update actually applies, identical on every
            # device, so the vector replicates cleanly
            from ..telemetry import health as _health
            hvec = _health.compute(hspec, loss, old_tr,
                                   tuple(red_grads), new_params,
                                   due=due)
            if irows is not None:
                import jax.numpy as jnp
                hvec = jnp.concatenate([hvec, irows])
            if hspec.skip:
                new_params, new_states, aux = _health.gate_update(
                    hvec, new_params, old_tr, new_states, tstate_vals,
                    aux, tuple(param_vals[i] for i in mutated_idx))
                if new_residuals:
                    # a skipped step must not keep the poisoned
                    # error-feedback either
                    new_residuals = _health.gate(
                        hvec, new_residuals, residual_vals)
            return loss, new_params, new_states, aux, \
                new_residuals, hvec

        if use_residual and self._residual_vals is None:
            repl_dp = NamedSharding(self.mesh, P(axis))
            self._residual_vals = tuple(
                jax.device_put(
                    jnp.zeros((n_dp,) + self._params[i].data().shape,
                              jnp.float32), repl_dp)
                for i in tr_idx)

        batch = P(self.dp_axis)
        repl = P()
        res_spec = P(axis)
        # check_vma=False: the quantized collectives are built on
        # all_gather, whose results the vma system types as "varying"
        # even though every device computes the identical sum — the
        # P() out_specs are mathematically sound (update inputs are
        # bit-identical across the axis)
        out_specs = (repl, repl, repl, repl, res_spec)
        in_specs = (repl, repl, repl, batch, batch, repl, res_spec)
        if hspec is not None:
            out_specs = out_specs + (repl,)
            in_specs = in_specs + (repl,)           # the due flag
            if ispec is not None and ispec.inject:
                in_specs = in_specs + (repl,)       # the ctl row
        mapped = shard_map(
            full, mesh=self.mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=False)
        # the wire auditor traces this (the compressed path dispatches
        # the jit directly, skipping the tiered-AOT seam where every
        # other variant registers)
        self._compressed_fn = mapped
        # donate optimizer state and (2bit) residuals — both are dead
        # the moment their successors exist
        # the observatory harvest + persist-entry hash must see the
        # SAME donate tuple the jit bakes, or the residual buffers
        # read as non-donated (false MXL308, understated savings)
        self._full_donate = (1, 6) if use_residual else (1,)
        self._full_step = jax.jit(
            mapped, donate_argnums=self._full_donate)

    def _zero_specs(self):
        """shard_map in/out PartitionSpecs shared by the ZeRO single-
        step and bulked builders: params/scalars/keys replicated,
        optimizer-state leaves sharded on their leading dp row, batch
        inputs on the dp axis."""
        from jax.sharding import PartitionSpec as P
        return P(), P(self.dp_axis), P(self.dp_axis)

    def _build_full_step_zero(self):
        """The fused step with the WEIGHT UPDATE sharded over the dp
        axis (ZeRO-1/2, arXiv 2004.13336; docs/zero.md): shard_map
        over the mesh, per-device forward/backward on the local batch
        shard, then — per trainable param — the gradient is reduced
        onto each member's 1/N flat slice (stage 2: one fused
        reduce-scatter, optionally int8-wire; stage 1: all-reduce +
        local slice), the fused optimizer rule updates ONLY that slice
        against the member's (1, chunk) state leaves, and the updated
        weight slices are all-gathered back into the replicated
        param.  Optimizer state never exists replicated: per-member
        HBM and update FLOPs drop ~dp x, inside the same single
        donated program.

        Numerics: the update is pointwise in the flat param
        (``zero.POINTWISE_RULES``), so slice-update + gather computes
        exactly the replicated update's values — fp32-parity with
        stage 0 is tier-1 asserted for SGD-momentum and Adam."""
        import jax
        import jax.lax as lax
        from ._compat import shard_map
        from .collectives import (sharded_weight_update,
                                  quantized_psum,
                                  quantized_reduce_scatter)

        rule = self._rule
        opt = self.optimizer
        n_scalars = len(rule.scalars(opt, 0, 1))
        tr_idx = self._tr_idx
        traced = self._traced_fn
        axis = self.dp_axis
        n_dp = int(self.mesh.shape[axis])
        stage = self._zero_stage
        quantized = self._compression_cfg is not None
        hspec = self._health_spec
        ispec = hspec.integrity if hspec is not None else None
        other_axes = tuple(a for a in self.mesh.axis_names
                           if a != axis)
        mutated_idx = self._mutated_idx

        def full(param_vals, tstate_vals, scalar_vals, input_vals,
                 label_val, key_raw, due=None, ictl=None):
            # per-device dropout keys decorrelate across the axis
            # (same scheme as the compressed step)
            dev_key = jax.random.key_data(jax.random.fold_in(
                jax.random.wrap_key_data(key_raw),
                lax.axis_index(axis)))
            loss, grads, aux = traced(param_vals, input_vals,
                                      label_val, dev_key)
            # stage 1 materializes the full reduced gradients (the
            # all-reduce leg) — health reads them for free.  Stage 2
            # never does: only the scattered slices exist, and health
            # derives its per-param squared sums FROM the slices (one
            # (T,)-vector psum — telemetry.health.compute_sharded), so
            # the gradient wire stays reduce-scatter with health on.
            reduce_full = stage == 1
            collect_sq = hspec is not None and not reduce_full
            import jax.numpy as jnp
            red_grads = []
            g_slices = []
            new_params, new_states = [], []
            for j, i in enumerate(tr_idx):
                scal = tuple(scalar_vals[j * n_scalars + k]
                             for k in range(n_scalars))
                # strip the (1, chunk) local row to the flat slice
                st = tuple(s[0] for s in tstate_vals[j])

                def upd(p_s, g_s, *st_s, _scal=scal):
                    # the grad leg reduced a SUM over members; the
                    # global-batch-mean gradient is sum/n (matching
                    # the stage-0 step's implicit pmean)
                    g_mean = g_s / n_dp
                    if collect_sq:
                        # capture the slice the update applies (free —
                        # it exists either way); the squared-sum
                        # reductions run under the `due` cond below
                        g_slices.append(g_mean)
                    res = rule.apply(opt, p_s, g_mean,
                                     tuple(st_s), *_scal)
                    if isinstance(res, tuple) and \
                            isinstance(res[1], tuple):
                        return res
                    return res[0], tuple(res[1:])

                if reduce_full:
                    # stage 1's all-reduce leg keeps the int8 wire
                    # when compression is configured (quantized_psum,
                    # the same exchange the stage-0 compressed step
                    # runs) — composing zero+int8 must never silently
                    # widen the gradient wire back to fp32
                    rg = quantized_psum(grads[j], axis) if quantized \
                        else lax.psum(grads[j], axis)
                    red_grads.append(rg / n_dp)
                    new_w, new_st = sharded_weight_update(
                        param_vals[i], rg, st, upd, axis,
                        grad_reduce="local")
                elif quantized:
                    new_w, new_st = sharded_weight_update(
                        param_vals[i], grads[j], st, upd, axis,
                        grad_reduce=lambda flat:
                            quantized_reduce_scatter(flat, axis))
                else:
                    new_w, new_st = sharded_weight_update(
                        param_vals[i], grads[j], st, upd, axis)
                new_params.append(new_w)
                # re-add the leading local dp row for the P(dp) out
                new_states.append(tuple(s[None] for s in new_st))
            new_params, new_states = tuple(new_params), \
                tuple(new_states)
            loss = lax.pmean(loss, axis)
            aux = tuple(lax.pmean(a, axis) for a in aux)
            if hspec is None:
                return loss, new_params, new_states, aux
            from ..telemetry import health as _health
            old_tr = tuple(param_vals[i] for i in tr_idx)
            irows = None
            if ispec is not None:
                from ..elastic import integrity as _integrity
                if reduce_full:
                    # stage 1's replicated post-exchange gradients
                    # carry the agreement audit (a corrupt_grad/
                    # corrupt_wire drill flips the targeted device's
                    # copy); stage 2 never materializes them — its
                    # spec drops the grad rows and corrupt_param (the
                    # host drill on the replicated param inputs) is
                    # the end-to-end exercise
                    red_grads = list(_integrity.maybe_corrupt(
                        ispec, ictl, tuple(red_grads), axis))
                irows = _integrity.body_rows(
                    ispec, axis, other_axes, old_tr,
                    tuple(red_grads) if reduce_full else None,
                    due=due)
            if reduce_full:
                hvec = _health.compute(hspec, loss, old_tr,
                                       tuple(red_grads), new_params,
                                       due=due)
            else:
                # the per-slice square sums + psum run only on sampled
                # steps (same `due` cond as health.compute — an
                # un-sampled step must not pay the reduction passes);
                # the skip gate reads the stats every step, and a
                # caller without a sampling schedule (due=None)
                # computes unconditionally
                def _sq_sums():
                    return lax.psum(jnp.stack(
                        [jnp.sum(jnp.square(g.astype(jnp.float32)))
                         for g in g_slices]), axis)
                if due is None or hspec.skip:
                    sq_global = _sq_sums()
                else:
                    sq_global = lax.cond(
                        due > 0, _sq_sums,
                        lambda: jnp.zeros((len(tr_idx),),
                                          jnp.float32))
                hvec = _health.compute_sharded(
                    hspec, loss, old_tr,
                    [sq_global[j] for j in range(len(tr_idx))],
                    new_params, due=due)
            if irows is not None:
                hvec = jnp.concatenate([hvec, irows])
            if hspec.skip:
                new_params, new_states, aux = _health.gate_update(
                    hvec, new_params, old_tr, new_states, tstate_vals,
                    aux, tuple(param_vals[i] for i in mutated_idx))
            return loss, new_params, new_states, aux, hvec

        repl, state_spec, batch = self._zero_specs()
        out_specs = (repl, repl, state_spec, repl)
        in_specs = (repl, state_spec, repl, batch, batch, repl)
        if hspec is not None:
            out_specs = out_specs + (repl,)
            in_specs = in_specs + (repl,)           # the due flag
            if ispec is not None and ispec.inject:
                in_specs = in_specs + (repl,)       # the ctl row
        # check_vma=False for the same reason as the compressed step:
        # all_gather-built outputs are vma-typed "varying" though every
        # member computes identical values
        mapped = shard_map(
            full, mesh=self.mesh, in_specs=in_specs,
            out_specs=out_specs, check_vma=False)
        # the bulked builder scans the PER-DEVICE body; _full_fn holds
        # the mapped twin, which eval_shape can trace at global avals
        # (a persist hit's mutated_idx recovery runs the Python body)
        self._zero_body = full
        self._full_fn = mapped
        self._full_donate = (1,)
        self._full_step = jax.jit(mapped,
                                  donate_argnums=self._full_donate)

    # -- persistent compile cache (docs/compile_cache.md) -----------------
    def _persist_name(self) -> str:
        """Stable persistent-tier identity for this trainer's fused
        step: block name + a hash over everything structural that the
        compiled program bakes (param shapes/dtypes, trainable set,
        optimizer class, mesh axes/sizes, dp axis).  A warm-start
        manifest pins the save-time name (``_persist_pin``) so gluon
        auto-naming drift cannot orphan on-disk entries."""
        if self._persist_pin is not None:
            return self._persist_pin
        import hashlib
        from .. import telemetry
        integ_sig = self._integrity_sig()
        parts = (type(self.optimizer).__name__,
                 tuple((tuple(p.data().shape), str(p.data().dtype))
                       for p in self._params),
                 tuple(self._tr_idx),
                 tuple((str(k), int(v))
                       for k, v in self.mesh.shape.items()),
                 self.dp_axis,
                 # health config is baked into the program's output
                 # arity — a flip must key fresh persistent entries;
                 # the ZeRO stage is baked into the program's
                 # collectives AND state avals, ditto — appended only
                 # when nonzero so stage-0 hashes (and with them every
                 # pre-ZeRO manifest + persisted executable) survive
                 # this release unchanged
                 telemetry.health.trace_signature()) + (
                     # integrity fingerprint rows widen the health
                     # vector (and a drill adds the ctl input) —
                     # appended only when armed so single-device and
                     # integrity-off hashes stay stable
                     (integ_sig,) if integ_sig is not None else ()
                 ) + (
                     (self._zero_stage,) if self._zero_stage else ()
                 ) + (
                     # the plan pin: a plan-driven trainer's rules are
                     # baked into the executables' shardings; appended
                     # only when a plan exists so every pre-planner
                     # hash (and persisted executable) still serves
                     (self.plan.struct_hash(),)
                     if self.plan is not None else ())
        h = hashlib.sha256(repr(parts).encode()).hexdigest()[:16]
        return f"spmd_full_step_{self.block.name}_{h}"

    def _struct_hash(self) -> str:
        """Mesh-size-independent structural identity: optimizer class,
        param shapes/dtypes, trainable set, dp-axis name.  The reshard
        warm-start path compares THIS (the persist-name hash bakes the
        mesh sizes, which legitimately differ across a reshard) so a
        manifest from a different model can never be adopted."""
        import hashlib
        from .. import telemetry
        integ_struct = self._integrity_struct_sig()
        parts = (type(self.optimizer).__name__,
                 tuple((tuple(p.data().shape), str(p.data().dtype))
                       for p in self._params),
                 tuple(self._tr_idx),
                 self.dp_axis,
                 # stage appended only when nonzero — see _persist_name
                 telemetry.health.trace_signature()) + (
                     # mesh-size-independent integrity identity
                     # (elastic.integrity.struct_signature): NOT n_dp
                     # — the reshard path legitimately changes it, and
                     # a dp=1 save (no fingerprint rows) must still
                     # warm-reshard onto dp>1 (re-AOT either way)
                     (integ_struct,) if integ_struct is not None
                     else ()
                 ) + (
                     (self._zero_stage,) if self._zero_stage else ()
                 ) + (
                     # mesh-size-independent plan identity: rules +
                     # axis NAMES (the reshard path legitimately
                     # changes sizes); appended only when a plan exists
                     (self.plan.struct_hash(ignore_sizes=True),)
                     if self.plan is not None else ())
        return hashlib.sha256(repr(parts).encode()).hexdigest()[:16]

    def _note_wire(self, suffix, pyfn, vals, compressed=False,
                   program=None):
        """Register one fused-step variant with the wire auditor
        (``analysis.wire_passes`` — MXL8xx): the pure fn + aval
        signature (no live arrays), the plan/mesh/role context the
        leg classifier needs, the trainable-param census the derived
        dense-dp leg model needs, and the observatory program name
        the MXL804 reconciliation reads.  Never raises."""
        try:
            import numpy as _np
            from ..analysis import wire_passes as _wire
            hs = self._health_spec
            pbytes = []
            for i in self._tr_idx:
                d = self._params[i].data()
                dt = _np.dtype(d.dtype)
                n = 1
                for s in d.shape:
                    n *= int(s)
                pbytes.append((self._params[i].name, n * dt.itemsize,
                               str(dt.name)))
            _wire.note_step(
                f"spmd:{self.block.name}", suffix, pyfn, vals,
                plan=self.plan, mesh_axes=dict(self.mesh.shape),
                dp_axis=self.dp_axis, zero_stage=self._zero_stage,
                compressed=compressed,
                # with hspec.skip the health vector feeds gate_update
                # (load-bearing, so the liveness slice already keeps
                # its rows primal) — only the sampled configuration
                # carries the "stats ride the cond gate" claim
                sampled=hs is not None and not hs.skip,
                program=program
                if program is not None else f"spmd_full_step{suffix}",
                params_bytes=pbytes,
                obs_outputs=(-1,) if hs is not None else ())
        except Exception:
            pass

    def _tiered_exec(self, suffix, jitted, pyfn, vals, donate):
        """Resolve the dispatchable for one fused-step variant:
        persistent tier (reload — no trace, no compile) -> fresh AOT
        ``lower().compile()`` (serialized back to disk when the tier is
        on).  The explicit AOT step runs even with the persistent tier
        OFF: it costs nothing over the jit path's implicit first-call
        compile and gives the memory observatory an executable to
        harvest.  On any failure returns ``jitted`` unchanged, so the
        tier can cost time, never a step."""
        from ..engine import persist as _persist
        self._note_wire(suffix, pyfn, vals)
        name = self._persist_name() + suffix
        try:
            import jax
            avals = _persist.aval_sig(vals)
            if not self._trace_seen[0] and \
                    _persist.contains(name, (), donate, avals):
                # a persist hit skips the Python trace, and with it the
                # mutated_idx discovery (BatchNorm-aux write-back
                # routing) — one abstract trace recovers it
                jax.eval_shape(pyfn, *vals)
            fn, _src = _persist.tiered_compile(
                name, jitted, vals, donate=donate,
                op_label=f"spmd_full_step{suffix}")
            return fn
        except Exception as e:
            from .. import telemetry
            telemetry.record_event(
                "persist_error", op=f"spmd_full_step{suffix}",
                error=f"aot demoted: {e!r}"[:300])
            return jitted

    def _record_variant(self, suffix, vals, k_steps, repeated):
        """Manifest row for :meth:`save_signature`: the data-dependent
        avals of one compiled variant (params/optimizer-state avals are
        re-derived locally at warm-start time)."""
        from ..engine import persist as _persist
        from jax import tree_util
        scal, x, y, key = vals[2], vals[3], vals[4], vals[5]
        row = {
            "suffix": suffix,
            "k_steps": k_steps, "repeat": bool(repeated),
            "inputs": _persist.sig_to_json(_persist.aval_sig(x)),
            "label": _persist.sig_to_json(_persist.aval_sig([y]))[0],
            "key": _persist.sig_to_json(_persist.aval_sig([key]))[0],
            "scalars": _persist.sig_to_json(_persist.aval_sig(
                tree_util.tree_leaves(scal))),
        }
        if len(vals) > 6:
            # trailing extras (the health plane's due flag): recorded
            # so warm_start can rebuild the exact call signature
            row["extra"] = _persist.sig_to_json(
                _persist.aval_sig(list(vals[6:])))
        self._var_avals[(k_steps or 0, bool(repeated))] = row

    def _dispatch_full(self, vals):
        """One fused-step dispatch through the tiered executable.

        ``_full_exec`` caches ``({aval sig: executable}, jitted)`` —
        per-signature so an aval drift (e.g. a changed batch size)
        resolves its OWN executable through the tier (own disk entry,
        warm restarts for both shapes) instead of raising per step;
        a signature whose AOT call still fails is demoted to the jit
        path permanently.  The cache is discarded whenever
        ``self._full_step`` is rebound (rebuilds, test seams), so the
        jit attribute stays the source of truth."""
        from ..engine import persist as _persist
        jit_fn = self._full_step
        if (0, False) not in self._var_avals:
            self._record_variant("", vals, None, False)
        cached = self._full_exec
        if cached is None or cached[1] is not jit_fn:
            cached = ({}, jit_fn)
            self._full_exec = cached
        by_sig = cached[0]
        s = _persist.aval_sig(vals)
        fn = by_sig.get(s)
        if fn is None:
            fn = self._tiered_exec("", jit_fn, self._full_fn, vals,
                                   self._full_donate)
            by_sig[s] = fn
        if fn is jit_fn:
            return fn(*vals)
        try:
            return fn(*vals)
        except TypeError:
            by_sig[s] = jit_fn        # cached demotion, not per-step
            return jit_fn(*vals)

    def save_signature(self, path: str) -> str:
        """Write the warm-start manifest for this trainer's compiled
        step variants: mesh axes/sizes, dp axis, per-param sharding
        layout, aux write-back routing, and the data-dependent input
        avals.  A fresh process with the same model/optimizer/mesh
        construction feeds it to :meth:`warm_start` to precompile the
        fused SPMD program (persistent-tier reload when
        ``MXTPU_COMPILE_CACHE_DIR`` holds it) before the first batch.
        Requires at least one successful fused ``step()`` /
        ``step_multi()``; returns ``path``."""
        import json
        import os as _os
        from ..engine import persist as _persist
        if not self._var_avals or self._params is None:
            raise MXNetError(
                "save_signature: run at least one successful fused "
                "step() / step_multi() first")
        shardings = []
        for p in self._params:
            try:
                shardings.append(str(p.data()._data.sharding.spec))
            except AttributeError:
                shardings.append("")
        manifest = {
            "zero": self._zero_record(),
            # the canonical plan pin (docs/parallelism.md): None for
            # legacy-arg trainers, so pre-planner manifests compare
            # equal on them
            "plan": self.plan.to_record() if self.plan is not None
            else None,
            "format": 1, "kind": "spmd_full_step",
            "fingerprint": _persist.fingerprint(),
            "persist_name": self._persist_name(),
            "struct": self._struct_hash(),
            "block": self.block.name,
            "optimizer": type(self.optimizer).__name__,
            "mesh": {str(k): int(v)
                     for k, v in self.mesh.shape.items()},
            "dp_axis": self.dp_axis,
            "param_shardings": shardings,
            "n_args": self._n_args,
            "tr_idx": [int(i) for i in self._tr_idx],
            "mutated_idx": [int(i) for i in self._mutated_idx],
            "variants": [self._var_avals[k]
                         for k in sorted(self._var_avals)],
        }
        tmp = path + f".tmp{_os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
        _os.replace(tmp, path)
        return path

    def _zero_record(self):
        """The warm-start/checkpoint manifest's ZeRO layout pin:
        stage, dp size, and the per-param flat shard slices
        ``[name, size, padded, chunk]`` (docs/zero.md).  None when the
        update is not sharded — so pre-ZeRO manifests compare equal on
        a stage-0 trainer."""
        if not self._zero_stage:
            return None
        from . import zero as _zero
        n_dp = int(self.mesh.shape[self.dp_axis])
        return {"stage": int(self._zero_stage), "dp": n_dp,
                "slices": _zero.slice_record(self._params,
                                             self._tr_idx, n_dp)}

    def warm_start(self, path: str) -> bool:
        """Precompile the fused step variants recorded in a
        :meth:`save_signature` manifest before the first batch arrives
        — a persistent-tier reload when the cache dir holds the
        executables, a fresh AOT compile otherwise.  Verifies the mesh
        layout (axis names + sizes), optimizer class, and the
        structural hash against the manifest; any mismatch (or any
        error) returns False and the first step compiles as usual.
        Requires ``fuse_step=True`` with a fused optimizer rule."""
        import json
        import numpy as np
        from .. import autograd, telemetry
        from ..engine import persist as _persist
        from .. import ndarray as nd

        def _fail(reason):
            telemetry.record_event("warm_start", name="spmd_full_step",
                                   ok=False, reason=reason)
            return False

        try:
            with open(path) as f:
                m = json.load(f)
        except (OSError, ValueError) as e:
            return _fail(f"unreadable manifest: {e!r}"[:300])
        if m.get("kind") != "spmd_full_step" or m.get("format") != 1:
            return _fail("not an spmd_full_step manifest")
        if m.get("fingerprint") != _persist.fingerprint():
            return _fail("environment fingerprint mismatch "
                         "(jax/jaxlib/platform/salt)")
        if not (self._fuse_step and self._rule is not None):
            return _fail("trainer has no fused step "
                         "(fuse_step=False or no fused rule)")
        if self._compression_cfg is not None:
            return _fail("gradient compression is not covered by "
                         "warm-start manifests")
        if self._donation_poisoned is not None:
            return _fail("trainer is poisoned")
        mesh_now = {str(k): int(v) for k, v in self.mesh.shape.items()}
        resharded = False
        if mesh_now != m.get("mesh") or \
                self.dp_axis != m.get("dp_axis"):
            # mesh-CHANGE restart (ROADMAP item 5): same axis names +
            # dp axis but different sizes is no longer a hard reject —
            # the step re-AOTs on the new mesh before the first batch
            # (the persist identity hashes the mesh, so the persistent
            # tier keys fresh entries for the new layout; params/state
            # reshard at checkpoint-restore time)
            saved = m.get("mesh") or {}
            if self.dp_axis != m.get("dp_axis") or \
                    set(saved) != set(mesh_now):
                return _fail(f"mesh layout mismatch: manifest "
                             f"{m.get('mesh')}/{m.get('dp_axis')!r} vs "
                             f"current {mesh_now}/{self.dp_axis!r}")
            resharded = True
        if type(self.optimizer).__name__ != m.get("optimizer"):
            return _fail("optimizer class mismatch")
        try:
            variants = list(m["variants"])
            ref = min(variants, key=lambda v: bool(v["k_steps"]))
            in_avals = _persist.sig_from_json(ref["inputs"])
            lbl_aval = _persist.sig_from_json([ref["label"]])[0]
            shapes = [a[0] for a in in_avals]
            lbl_shape = lbl_aval[0]
            if ref.get("k_steps") and not ref.get("repeat"):
                shapes = [s[1:] for s in shapes]
                lbl_shape = lbl_shape[1:]
            args = [nd.array(np.zeros(s, dtype=np.dtype(a[1])))
                    for s, a in zip(shapes, in_avals)]
            label = nd.array(np.zeros(
                lbl_shape, dtype=np.dtype(lbl_aval[1])))
        except Exception as e:
            return _fail(f"bad aval record: {e!r}"[:300])
        if resharded:
            ndp = int(mesh_now.get(self.dp_axis, 1))
            if any(s and s[0] % ndp
                   for s in list(shapes) + [lbl_shape]):
                return _fail(
                    f"global batch does not divide the new dp size "
                    f"{ndp}; cannot reshard the input layout")

        import jax
        prev = autograd.set_training(True)
        try:
            if self._params is None:
                self._setup(args)
            # the manifest's executables were compiled under SOME
            # health config; adopt the current one before building so
            # the first step doesn't immediately evict the warm start
            self._refresh_health()
            # the ZeRO layout is baked into the serialized executables
            # (state avals, collectives): a stage/slice mismatch must
            # fail open to cold compile, never adopt stale entries —
            # checked BEFORE the opaque struct-hash comparison so the
            # rejection reason names the actual cause.  A resharded
            # warm start re-derives its slices on the new dp size, so
            # THERE only the stage must agree.
            # the plan pin is compared FIRST and by field, so a
            # rejection names the exact diverging rule instead of an
            # opaque hash (fail-open either way: cold compile, never a
            # crash).  The reshard path ignores axis SIZES — a mesh
            # change is its whole point — but rules/roles must agree.
            from . import planner as _planner
            plan_diff = _planner.diff_records(
                m.get("plan"),
                self.plan.to_record() if self.plan is not None
                else None,
                ignore_sizes=resharded)
            if plan_diff is not None:
                return _fail(f"sharding-plan mismatch: {plan_diff}")
            mzero = m.get("zero")
            mstage = int((mzero or {}).get("stage", 0))
            if resharded:
                if mstage != self._zero_stage:
                    return _fail(
                        f"zero stage mismatch: manifest stage "
                        f"{mstage} vs current {self._zero_stage} "
                        "(reshard path)")
            else:
                # structural comparison, like the persist hash: the
                # slice NAMES carry gluon auto-naming (process-scoped
                # prefixes); stage/dp/[size, padded, chunk] are what
                # the serialized executables bake
                def _zkey(rec):
                    if not rec:
                        return None
                    return (int(rec.get("stage", 0)),
                            int(rec.get("dp", 0)),
                            tuple(tuple(int(x) for x in row[1:])
                                  for row in rec.get("slices") or ()))
                if _zkey(mzero) != _zkey(self._zero_record()):
                    return _fail(
                        f"zero sharding layout mismatch: manifest "
                        f"{mzero!r} vs current "
                        f"{self._zero_record()!r}")
            # structural hash must match before adopting the identity —
            # the hash part of the persist name covers param
            # shapes/dtypes, trainable set, optimizer, and mesh layout.
            # A resharded warm start keeps its LOCAL identity (the
            # saved hash bakes the old mesh, and the new mesh must key
            # its own persistent entries — re-AOT, not reuse), so THERE
            # the mesh-independent struct hash carries the "manifest
            # describes this model" invariant instead
            if resharded:
                if m.get("struct") != self._struct_hash():
                    return _fail(
                        "structural hash mismatch: the manifest "
                        "describes a different model/optimizer "
                        "configuration (reshard path)")
            elif str(m.get("persist_name", "")).rsplit("_", 1)[-1] \
                    != self._persist_name().rsplit("_", 1)[-1]:
                return _fail("structural hash mismatch: the manifest "
                             "describes a different model/optimizer/"
                             "mesh configuration")
            if self._fwd_bwd is None:
                self._build_fwd_bwd(args, label)
            if self._full_fn is None:
                if self._zero_stage:
                    self._build_full_step_zero()
                else:
                    self._build_full_step()
            # AFTER the builders: _build_fwd_bwd rebinds
            # self._mutated_idx to a fresh list, which would silently
            # drop the adopted aux routing (BatchNorm write-backs)
            if not resharded:
                self._persist_pin = m["persist_name"]
            self._mutated_idx[:] = [int(i) for i in m["mutated_idx"]]
            self._trace_seen[0] = True
            param_vals = tuple(p.data()._data for p in self._params)
            state_vals = self._state_vals()
            for v in variants:
                try:
                    x_sds = tuple(
                        jax.ShapeDtypeStruct(a[0], np.dtype(a[1]))
                        for a in _persist.sig_from_json(v["inputs"]))
                    la = _persist.sig_from_json([v["label"]])[0]
                    y_sds = jax.ShapeDtypeStruct(la[0], np.dtype(la[1]))
                    ka = _persist.sig_from_json([v["key"]])[0]
                    k_sds = jax.ShapeDtypeStruct(ka[0], np.dtype(ka[1]))
                    scal_avals = _persist.sig_from_json(v["scalars"])
                    scal_sds = [jax.ShapeDtypeStruct(
                        a[0], np.dtype(a[1])) for a in scal_avals]
                except (TypeError, ValueError) as e:
                    return _fail(f"bad variant avals: {e!r}"[:300])
                try:
                    extra_sds = tuple(
                        jax.ShapeDtypeStruct(a[0], np.dtype(a[1]))
                        for a in _persist.sig_from_json(
                            v.get("extra") or []))
                except (TypeError, ValueError) as e:
                    return _fail(f"bad variant avals: {e!r}"[:300])
                k = v.get("k_steps")
                if k:
                    kk = (int(k), bool(v.get("repeat")))
                    vals = (param_vals, state_vals, scal_sds[0],
                            x_sds, y_sds, k_sds) + extra_sds
                    fn = self._multi_step_cache.get(kk)
                    if fn is None:
                        fn = self._build_full_step_multi(*kk)
                    call = self._tiered_exec(
                        v["suffix"], fn, self._multi_fns[kk], vals,
                        (0, 1))
                    self._multi_exec[kk] = (
                        {_persist.aval_sig(vals): call}, fn)
                else:
                    vals = (param_vals, state_vals, tuple(scal_sds),
                            x_sds, y_sds, k_sds) + extra_sds
                    call = self._tiered_exec(
                        "", self._full_step, self._full_fn, vals,
                        self._full_donate)
                    self._full_exec = (
                        {_persist.aval_sig(vals): call},
                        self._full_step)
                self._var_avals[(int(k or 0),
                                 bool(v.get("repeat")))] = v
        except Exception as e:
            # the never-raises contract: a mismatched/stale manifest
            # (wrong input widths feeding deferred init, a builder
            # failure, ...) degrades to the cold path, not a crash
            return _fail(f"warm-start failed: {e!r}"[:300])
        finally:
            autograd.set_training(prev)
        self.warm_started = True
        telemetry.record_event("warm_start", name="spmd_full_step",
                               ok=True, resharded=resharded)
        return True

    # -- elastic protocol (docs/elasticity.md) ----------------------------
    def _elastic_export(self):
        """Everything ``elastic.CheckpointManager`` persists for this
        trainer: params (incl. frozen/BatchNorm aux), optimizer-state
        leaves, compression residuals, update counters, mesh layout +
        per-param sharding specs, and the warm-start persist
        identity."""
        if self._params is None:
            raise MXNetError(
                "nothing to checkpoint yet: run a step (or restore) "
                "before save()")
        from ..elastic import reshard as _reshard
        opt = self.optimizer
        params = []
        for p in self._params:
            d = p.data()
            try:
                spec = _reshard.spec_to_str(d._data.sharding.spec)
            except AttributeError:
                spec = "()"
            params.append((p.name, d._data, spec))
        states = []
        for i in self._tr_idx:
            leaves: List[NDArray] = []
            _flatten(self._states[i], leaves)
            for j, leaf in enumerate(leaves):
                states.append((i, j, leaf._data))
        step = max(opt._index_update_count.values(),
                   default=int(opt.num_update))
        return {
            "kind": "spmd", "step": int(step),
            "optimizer": type(opt).__name__,
            "update_counts": dict(opt._index_update_count),
            "num_update": int(opt.num_update),
            "mesh": {str(k): int(v) for k, v in self.mesh.shape.items()},
            "dp_axis": self.dp_axis,
            "persist_name": self._persist_name(),
            # the ZeRO layout pin: restore converts sharded state rows
            # to ANY target layout (other dp size, or gathered full
            # shape on a ZeRO-off trainer) — docs/zero.md matrix
            "zero": self._zero_record(),
            # the plan pin (audit trail; restore does NOT reject on a
            # differing plan — a cross-plan restore IS the portability
            # matrix, routed through the reshard path)
            "plan": self.plan.to_record() if self.plan is not None
            else None,
            "params": params, "states": states,
            "residuals": list(self._residual_vals or ()),
        }

    def _elastic_restore(self, payload):
        """Apply a checkpoint payload: params + optimizer state land
        on THIS trainer's mesh (the reshard path when the checkpoint
        was saved on a different mesh — fp32-exact, the layout move
        never touches element values), counters and poison state are
        rewound, and the placement cache is dropped."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from .. import telemetry
        from ..elastic import reshard as _reshard

        self._ensure_setup_for_restore()
        mesh_now = {str(k): int(v) for k, v in self.mesh.shape.items()}
        saved_mesh = payload.get("mesh") or {}
        resharded = bool(saved_mesh) and saved_mesh != mesh_now
        repl = NamedSharding(self.mesh, P())

        from ..elastic.manager import align_params
        aligned = align_params([p.name for p in self._params],
                               payload["params"])
        plans = {}
        for p, (host, spec_str) in zip(self._params, aligned):
            d = p.data()
            if tuple(host.shape) != tuple(d.shape):
                raise MXNetError(
                    f"checkpoint param {p.name!r} has shape "
                    f"{tuple(host.shape)}, trainer expects "
                    f"{tuple(d.shape)}")
            # target layout = this trainer's sharding rule (plan or
            # callable) on the CURRENT mesh — the same consultation
            # point as _shard_params/_sharding_tuples, so a cross-PLAN
            # restore is just the reshard path with different specs
            spec = self._param_spec(p.name, d.shape)
            if resharded:
                plans[p.name] = _reshard.plan(
                    host.shape, _reshard.spec_from_str(spec_str),
                    saved_mesh, spec if spec is not None else P(),
                    mesh_now)
            d._set_data(_reshard.place(np.asarray(host), self.mesh,
                                       spec if spec is not None
                                       else P()))
        # optimizer-state portability matrix (docs/zero.md): the saved
        # layout (full, or ZeRO (n_src, chunk) rows) converts to THIS
        # trainer's layout by pure flat reshapes — fp32-exact — so a
        # ZeRO checkpoint restores onto any dp size and onto ZeRO-off
        # trainers, and a pre-ZeRO checkpoint restores sharded
        from . import planner as _planner
        from . import zero as _zero
        src_zero = int((payload.get("zero") or {}).get("stage", 0)) >= 1
        zero_spec = _planner.zero_state_sharding(self.mesh,
                                                 self.dp_axis)
        n_dp = int(self.mesh.shape.get(self.dp_axis, 1))
        for i, j, host in payload["states"]:
            if not (0 <= i < len(self._states)) or \
                    self._states[i] is None:
                raise MXNetError(
                    f"checkpoint optimizer-state leaf ({i},{j}) has "
                    "no slot in this trainer (optimizer mismatch?)")
            leaves: List[NDArray] = []
            _flatten(self._states[i], leaves)
            if j >= len(leaves):
                raise MXNetError(
                    f"checkpoint optimizer-state leaf ({i},{j}) out "
                    "of range (optimizer class mismatch?)")
            host = np.asarray(host)
            pshape = tuple(self._params[i].data().shape)
            if self._zero_stage:
                rows = _zero.reshard_host(host, pshape, n_dp)
                leaves[j]._set_data(jax.device_put(rows, zero_spec))
            elif src_zero:
                full = _zero.gather_host(host, pshape).astype(
                    leaves[j]._data.dtype, copy=False)
                leaves[j]._set_data(jax.device_put(full, repl))
            else:
                leaves[j]._set_data(jax.device_put(host, repl))
        residuals = payload.get("residuals") or []
        if self._compression_cfg is not None:
            if not residuals or resharded:
                # restart error feedback at zero (rebuilt lazily by
                # the compressed step): either the checkpoint predates
                # the first compressed step — keeping this process's
                # abandoned-timeline residuals would diverge from an
                # uninterrupted run — or the replica count changed and
                # per-REPLICA state has no exact mapping
                self._residual_vals = None
            else:
                res_dp = NamedSharding(self.mesh, P(self.dp_axis))
                self._residual_vals = tuple(
                    jax.device_put(np.asarray(h), res_dp)
                    for h in residuals)
        opt = self.optimizer
        counts = {int(k): int(v)
                  for k, v in (payload.get("update_counts") or
                               {}).items()}
        # rewind every per-device count dict, not just the alias the
        # last _set_current_context left behind
        for dev_counts in opt._all_index_update_counts.values():
            dev_counts.clear()
            dev_counts.update(counts)
        opt.num_update = int(payload.get("num_update",
                                         payload["step"]))
        self._donation_poisoned = None
        self._placed = {}
        if resharded:
            telemetry.record_event(
                "reshard", where="spmd_restore",
                saved_mesh=saved_mesh, mesh=mesh_now,
                moves={k: v for k, v in list(plans.items())[:8] if v})

    def recover(self, manager, step: Optional[int] = None) -> int:
        """Rebuild this trainer's donated buffers from the last
        committed checkpoint (or ``step``) and clear the poison latch —
        the recovery half of the donation-failure protocol.  Safe to
        call on a healthy trainer too (plain restore).  Returns the
        restored step.  Recovery FORKS the timeline: checkpoints newer
        than the restored step are invalidated, so a later crash can
        never resume from the abandoned run."""
        from ..elastic.manager import timed_recover
        return timed_recover(
            manager, self, "spmd", step=step,
            was_poisoned=self._donation_poisoned is not None)

    def save_states(self, fname: str) -> str:
        """Write the optimizer state (parity: ``gluon.Trainer.
        save_states``) in the PORTABLE full layout: ZeRO-sharded
        leaves are gathered to their param shapes on the host first,
        so the file loads onto any dp size and onto ZeRO-off trainers
        (fp32-exact — the gather is a flat reshape)."""
        import pickle
        from . import zero as _zero
        if self._params is None:
            raise MXNetError(
                "save_states: run a step (or restore) first")
        opt = self.optimizer
        states = {}
        for i in self._tr_idx:
            leaves: List[NDArray] = []
            _flatten(self._states[i], leaves)
            pshape = tuple(self._params[i].data().shape)
            hosts = []
            for leaf in leaves:
                host = np.asarray(leaf._data)
                hosts.append(_zero.gather_host(host, pshape)
                             if self._zero_stage else host)
            states[int(i)] = hosts
        blob = {
            "format": 1, "kind": "spmd_opt_states",
            "optimizer": type(opt).__name__,
            "update_counts": {int(k): int(v)
                              for k, v in
                              opt._index_update_count.items()},
            "num_update": int(opt.num_update),
            "states": states,
        }
        with open(fname, "wb") as f:
            pickle.dump(blob, f)
        return fname

    def load_states(self, fname: str):
        """Load a :meth:`save_states` file into THIS trainer's layout:
        full leaves re-shard onto the dp axis when ZeRO is on,
        replicate otherwise.  Optimizer class must match."""
        import jax
        import pickle
        from jax.sharding import NamedSharding, PartitionSpec as P
        from . import zero as _zero
        self._ensure_setup_for_restore()
        with open(fname, "rb") as f:
            blob = pickle.load(f)
        if not isinstance(blob, dict) or \
                blob.get("kind") != "spmd_opt_states":
            raise MXNetError(f"{fname!r} is not a "
                             "DataParallelTrainer save_states file")
        opt = self.optimizer
        if blob.get("optimizer") != type(opt).__name__:
            raise MXNetError(
                f"optimizer mismatch: file has "
                f"{blob.get('optimizer')!r}, trainer runs "
                f"{type(opt).__name__}")
        repl = NamedSharding(self.mesh, P())
        zero_spec = NamedSharding(self.mesh, P(self.dp_axis))
        n_dp = int(self.mesh.shape.get(self.dp_axis, 1))
        for i, hosts in blob["states"].items():
            i = int(i)
            if not (0 <= i < len(self._states)) or \
                    self._states[i] is None:
                raise MXNetError(
                    f"state for param index {i} has no slot in this "
                    "trainer (optimizer/trainable-set mismatch?)")
            leaves: List[NDArray] = []
            _flatten(self._states[i], leaves)
            if len(hosts) != len(leaves):
                raise MXNetError(
                    f"param index {i}: file has {len(hosts)} state "
                    f"leaves, trainer expects {len(leaves)}")
            pshape = tuple(self._params[i].data().shape)
            for leaf, host in zip(leaves, hosts):
                if self._zero_stage:
                    rows = _zero.reshard_host(host, pshape, n_dp)
                    leaf._set_data(jax.device_put(rows, zero_spec))
                else:
                    # a ZeRO save is always f32; cast to the slot's
                    # dtype (same contract as _elastic_restore) so the
                    # state avals the compiled step baked never drift
                    host = np.asarray(host).astype(
                        leaf._data.dtype, copy=False)
                    leaf._set_data(jax.device_put(host, repl))
        counts = {int(k): int(v)
                  for k, v in (blob.get("update_counts") or
                               {}).items()}
        for dev_counts in opt._all_index_update_counts.values():
            dev_counts.clear()
            dev_counts.update(counts)
        opt.num_update = int(blob.get("num_update", opt.num_update))

    # -- live elastic resize (docs/elasticity.md, "Live resize") ----------
    def _resize_check(self, mesh, allow_new_axes=False):
        """Raise ``MXNetError`` when this trainer cannot be resized
        onto ``mesh`` (the eligibility half of ``prepare_resize``).
        ``allow_new_axes`` (the plan-targeted path) permits the axis
        SET to change — a dp8 -> dp4 x tp2 plan resize — as long as
        the dp axis survives; the bare-mesh path keeps the strict
        sizes-only contract."""
        if self._params is None or not self._var_avals:
            raise MXNetError(
                "prepare_resize: run at least one successful fused "
                "step() / step_multi() first (the recorded variants "
                "are what the pre-warm compiles for the target mesh)")
        if not (self._fuse_step and self._rule is not None):
            raise MXNetError(
                "live resize requires fuse_step=True with a fused "
                "optimizer rule (the swap rebinds the fused step's "
                "compiled entries)")
        if self._compression_cfg is not None and not self._zero_stage:
            raise MXNetError(
                "live resize does not cover stage-0 gradient "
                "compression (per-replica error-feedback residuals "
                "have no exact mapping across a dp change); restart "
                "through the checkpoint reshard path instead")
        if self._donation_poisoned is not None:
            raise MXNetError(
                "trainer is poisoned; recover(manager) before "
                "resizing")
        mesh_now = {str(k): int(v) for k, v in self.mesh.shape.items()}
        mesh_new = {str(k): int(v) for k, v in mesh.shape.items()}
        if self.dp_axis not in mesh_new or (
                not allow_new_axes and
                set(mesh_now) != set(mesh_new)):
            raise MXNetError(
                f"resize target mesh axes {sorted(mesh_new)} must "
                f"match the current axes {sorted(mesh_now)} (only "
                "axis SIZES change in a bare-mesh live resize; pass "
                "a target ShardingPlan to change the axis set)")
        # (batch divisibility against the target dp size is validated
        # per data shape by prepare_resize's job construction — the
        # superset of the recorded rows — before any state is touched)

    def prepare_resize(self, mesh):
        """PRE-WARM a live resize: AOT-compile every recorded fused
        step variant (single + each ``step_multi(K)``) for the target
        ``mesh`` — through the persistent tier when it is on — while
        this trainer keeps training on its CURRENT mesh.  Returns an
        opaque staged bundle for :meth:`apply_resize`; on any failure
        the trainer is left exactly as it was.

        ``mesh`` may be a :class:`~mxnet_tpu.parallel.planner.
        ShardingPlan`: the target mesh then comes from the plan's
        axes, the target PARAM LAYOUT from its rules, and the swap
        adopts the plan — a plan-to-plan live resize (e.g. dp8 ->
        dp4 x tp2), not just a dp-size change.  The plan's zero
        stage (when set) must match the trainer's latched stage.

        The target-mesh programs are compiled purely from avals: param
        /state layouts come from :meth:`_sharding_tuples` (structural,
        mesh-parameterized), ZeRO state rows from
        ``zero.state_avals`` (the ``(n_dp, chunk)`` layout the swap
        will materialize), and the data avals from the recorded
        variant rows — so the swap later pays ZERO fresh compiles
        (tier-1 asserted; MXL503 watches the contract at runtime)."""
        import jax
        from ..engine import persist as _persist
        from . import planner as _planner
        from . import zero as _zero

        plan_b = None
        if isinstance(mesh, _planner.ShardingPlan):
            plan_b = mesh
            if plan_b.dp_axis != self.dp_axis:
                raise MXNetError(
                    f"target plan's dp_axis {plan_b.dp_axis!r} does "
                    f"not match the trainer's {self.dp_axis!r}")
            if plan_b.zero_stage is not None and \
                    int(plan_b.zero_stage) != self._zero_stage:
                raise MXNetError(
                    f"target plan pins zero_stage "
                    f"{plan_b.zero_stage}, trainer latched "
                    f"{self._zero_stage} at construction (the stage "
                    "decides the physical state layout and cannot "
                    "flip in a live resize)")
            if self._zero_stage and plan_b.param_rule() is not None:
                raise MXNetError(
                    "target plan's rules shard params, but this "
                    "trainer runs a ZeRO-sharded update — the same "
                    "exclusion as construction (ZeRO shards the "
                    "UPDATE of dp-replicated params; docs/zero.md); "
                    "resize to a rule-free plan or restart stage 0")
            mesh = plan_b.build_mesh()
        self._resize_check(mesh, allow_new_axes=plan_b is not None)
        self._refresh_health()
        n_b = int(mesh.shape[self.dp_axis])
        if plan_b is None and self.plan is not None:
            # mesh-only resize of a plan-driven trainer: the adopted
            # plan keeps the rules/roles but records the target axis
            # sizes (the plan object stays the source of truth)
            rec = self.plan.to_record()
            rec["axes"] = [[str(k), int(v)]
                           for k, v in mesh.shape.items()]
            plan_b = _planner.ShardingPlan.from_record(rec)
            plan_b._mesh = mesh
        rule_b = plan_b.param_rule() if plan_b is not None \
            else self._param_sharding

        param_sds = tuple(
            jax.ShapeDtypeStruct(tuple(p.data().shape),
                                 p.data()._data.dtype)
            for p in self._params)
        if self._zero_stage:
            state_sds = _zero.state_avals(self._params, self._tr_idx,
                                          self._states, n_b)
        else:
            state_sds = tuple(
                tuple(jax.ShapeDtypeStruct(tuple(v.shape), v.dtype)
                      for v in vals)
                for vals in self._state_vals())

        # every data shape this trainer has DISPATCHED must swap warm:
        # the recorded variant rows hold one (the first) data shape
        # per variant, while the per-signature exec caches hold them
        # all (a second batch size resolves its own executable without
        # a new row) — the job list is their union, deduped by the
        # data avals, each validated against the target dp size
        def _sds(entry):
            return jax.ShapeDtypeStruct(entry[0], np.dtype(entry[1]))

        jobs = {}

        def _add_job(k, rep, scal_sds, x_sds, y_sds, key_sds,
                     extra_sds):
            from ..engine import persist as _p
            for a in list(x_sds) + [y_sds]:
                shape = tuple(a.shape)
                stacked = k and not rep
                if not shape or (stacked and len(shape) < 2):
                    continue
                bdim = shape[1] if stacked else shape[0]
                if bdim % n_b:
                    raise MXNetError(
                        f"global batch dim {bdim} does not divide "
                        f"the target dp size {n_b}; cannot resize "
                        "without changing the batch layout")
            key = (k, rep, _p.aval_sig(
                list(scal_sds) + list(x_sds) + [y_sds, key_sds] +
                list(extra_sds)))
            jobs.setdefault(
                key, (list(scal_sds), tuple(x_sds), y_sds, key_sds,
                      tuple(extra_sds)))

        for (k, rep), row in self._var_avals.items():
            try:
                _add_job(
                    k, rep,
                    [_sds(a) for a in
                     _persist.sig_from_json(row["scalars"])],
                    [_sds(a) for a in
                     _persist.sig_from_json(row["inputs"])],
                    _sds(_persist.sig_from_json([row["label"]])[0]),
                    _sds(_persist.sig_from_json([row["key"]])[0]),
                    [_sds(a) for a in
                     _persist.sig_from_json(row.get("extra") or [])])
            except (TypeError, ValueError, KeyError) as e:
                raise MXNetError(
                    f"prepare_resize: bad recorded variant avals: "
                    f"{e!r}")
        n_p = len(self._params)
        n_state = sum(len(vals) for vals in self._state_vals())
        n_scal_1 = len(self._rule.scalars(self.optimizer, 0, 1)) \
            * len(self._tr_idx)
        sig_sources = []
        if self._full_exec is not None:
            sig_sources.extend((0, False, s)
                               for s in self._full_exec[0])
        for (k, rep), cached in self._multi_exec.items():
            sig_sources.extend((k, rep, s) for s in cached[0])
        for k, rep, sig in sig_sources:
            entries = list(sig[n_p + n_state:])
            n_scal = 1 if k else n_scal_1
            if len(entries) < n_scal + self._n_args + 2 or \
                    any(len(a) != 2 for a in entries):
                continue          # unreconstructable: skip, not fatal
            scal = [_sds(a) for a in entries[:n_scal]]
            rest = entries[n_scal:]
            x = [_sds(a) for a in rest[:self._n_args]]
            rest = rest[self._n_args:]
            _add_job(k, rep, scal, x, _sds(rest[0]), _sds(rest[1]),
                     [_sds(a) for a in rest[2:]])

        # the builders read self.mesh (shard_map mesh, batch
        # shardings, n_dp) and self._persist_name() (hashes the mesh):
        # rebind both to the TARGET for the build, restore after —
        # nothing dispatches in between, so the trainer never observes
        # the temporary binding
        saved = (self.mesh, self._full_step, self._full_fn,
                 self._zero_body, self._full_exec,
                 self._multi_step_cache, self._multi_fns,
                 self._multi_exec, self._persist_pin, self.plan,
                 self._param_sharding, self._health_spec,
                 self._health_built_sig)
        try:
            self.mesh = mesh
            # the target plan/rules drive the builders'
            # _sharding_tuples AND the persist identity during the
            # build; restored below — the live trainer never observes
            # the temporary binding
            self.plan = plan_b
            self._param_sharding = rule_b
            self._persist_pin = None        # the pin bakes the OLD mesh
            self._full_step = None
            self._full_fn = None
            self._zero_body = None
            self._full_exec = None
            self._multi_step_cache = {}
            self._multi_fns = {}
            self._multi_exec = {}
            # the integrity fingerprint rows bake the dp SIZE (one
            # all_gather lane per replica): the target-mesh programs
            # must be built against the TARGET spec, and the swap
            # adopts it — otherwise the first post-swap
            # _refresh_health would evict every pre-warmed executable
            # (a broken pre-warm contract, the exact MXL503 hazard)
            self._health_spec = None
            self._health_built_sig = None
            self._refresh_health()
            if self._zero_stage:
                self._build_full_step_zero()
            else:
                self._build_full_step()
            for (k, rep, _dsig) in sorted(
                    jobs, key=lambda j: (j[0], j[1], repr(j[2]))):
                scal_sds, x_sds, y_sds, k_sds, extra_sds = \
                    jobs[(k, rep, _dsig)]
                if k:
                    suffix = f"_k{k}" + ("r" if rep else "")
                    fn = self._multi_step_cache.get((k, rep))
                    if fn is None:
                        fn = self._build_full_step_multi(k, rep)
                    vals = (param_sds, state_sds, scal_sds[0],
                            x_sds, y_sds, k_sds) + extra_sds
                    call = self._tiered_exec(
                        suffix, fn, self._multi_fns[(k, rep)],
                        vals, (0, 1))
                    by_sig = self._multi_exec.setdefault(
                        (k, rep), ({}, fn))[0]
                    by_sig[_persist.aval_sig(vals)] = call
                else:
                    vals = (param_sds, state_sds, tuple(scal_sds),
                            x_sds, y_sds, k_sds) + extra_sds
                    call = self._tiered_exec(
                        "", self._full_step, self._full_fn, vals,
                        self._full_donate)
                    if self._full_exec is None:
                        self._full_exec = ({}, self._full_step)
                    self._full_exec[0][_persist.aval_sig(vals)] = call
            staged = {
                "mesh": mesh, "n_dp": n_b,
                "plan": plan_b, "param_sharding": rule_b,
                "full_step": self._full_step,
                "full_fn": self._full_fn,
                "zero_body": self._zero_body,
                "full_exec": self._full_exec,
                "multi_step_cache": self._multi_step_cache,
                "multi_fns": self._multi_fns,
                "multi_exec": self._multi_exec,
                "health_spec": self._health_spec,
                "health_built_sig": self._health_built_sig,
            }
        finally:
            (self.mesh, self._full_step, self._full_fn,
             self._zero_body, self._full_exec,
             self._multi_step_cache, self._multi_fns,
             self._multi_exec, self._persist_pin, self.plan,
             self._param_sharding, self._health_spec,
             self._health_built_sig) = saved
        return staged

    def apply_resize(self, staged):
        """RESHARD the live donated buffers onto the staged mesh and
        SWAP the pre-warmed executables in (the two downtime phases of
        a live resize; ``elastic.resize.ResizeController`` drives drain
        -> this).  Params (and replicated optimizer state) move
        through ``elastic.reshard.redistribute`` — the one-program
        donated layout move when the device sets coincide, the runtime
        transfer engine otherwise — so the move never holds model +
        state twice; ZeRO state rows change SHAPE across a dp change
        and convert through the exact flat-reshape path the checkpoint
        portability matrix uses, each source row deleted as its
        successor lands.  fp32-exact throughout: a layout move never
        touches element values.

        Raises on failure; the caller (the controller) crash-heals
        from the drain checkpoint via :meth:`_resize_swap` + a manager
        restore — the committed checkpoint makes every mid-move tear
        recoverable onto the NEW mesh."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..elastic import faults as _faults
        from ..elastic import reshard as _reshard
        from . import zero as _zero

        mesh_b = staged["mesh"]
        _faults.maybe_fire("resize_reshard")
        param_sh, _state_sh = self._sharding_tuples(
            mesh=mesh_b,
            rule=staged["param_sharding"] if "param_sharding" in
            staged else _RULE_UNSET)
        holders: List[NDArray] = [p.data() for p in self._params]
        targets = list(param_sh)
        if not self._zero_stage:
            flat: List[NDArray] = []
            _flatten(self._states, flat)
            holders.extend(flat)
            repl_b = NamedSharding(mesh_b, P())
            targets.extend(repl_b for _ in flat)
        srcs = [h._data for h in holders]
        if _faults._active:
            # donate-tuple discipline: every source here IS donated to
            # the move (redistribute donates its identity-jit inputs),
            # so the pre-filtered form is the whole list
            _faults.on_dispatch("resize_reshard", srcs, donate=None)
        moved = _reshard.redistribute(srcs, targets)
        for h, a in zip(holders, moved):
            h._set_data(a)
        if self._zero_stage:
            n_b = staged["n_dp"]
            zspec = NamedSharding(mesh_b, P(self.dp_axis))
            for i in self._tr_idx:
                leaves: List[NDArray] = []
                _flatten(self._states[i], leaves)
                pshape = tuple(self._params[i].data().shape)
                for leaf in leaves:
                    host = np.asarray(leaf._data)
                    rows = _zero.reshard_host(host, pshape, n_b)
                    old = leaf._data
                    leaf._set_data(jax.device_put(rows, zspec))
                    try:
                        old.delete()
                    except Exception:
                        pass
        _faults.maybe_fire("resize_swap")
        self._resize_swap(staged)
        self._note_resize_layouts()

    def _resize_swap(self, staged):
        """Rebind the trainer onto the staged mesh + pre-warmed
        executables (bindings only — buffer movement lives in
        :meth:`apply_resize`; the controller's crash-heal calls this
        directly and then restores the drain checkpoint INTO the new
        bindings)."""
        self.mesh = staged["mesh"]
        # a plan-targeted resize adopts the target plan + its rules as
        # the trainer's new source of truth (re-registered for the
        # MXL313 audit by _note_resize_layouts)
        if "plan" in staged:
            self.plan = staged["plan"]
            self._param_sharding = staged["param_sharding"]
        self._full_step = staged["full_step"]
        self._full_fn = staged["full_fn"]
        self._zero_body = staged["zero_body"]
        self._full_exec = staged["full_exec"]
        self._multi_step_cache = staged["multi_step_cache"]
        self._multi_fns = staged["multi_fns"]
        self._multi_exec = staged["multi_exec"]
        if "health_spec" in staged:
            # the target-mesh health/integrity spec the pre-warm built
            # against (its fingerprint rows bake the new dp size) —
            # adopting it keeps the first post-swap _refresh_health a
            # no-op, so the pre-warmed executables survive
            self._health_spec = staged["health_spec"]
            self._health_built_sig = staged["health_built_sig"]
        # the old pin (if any) baked the old mesh; the new mesh keys
        # its own persistent identities.  _fwd_bwd/_fused_update are
        # two-phase-path artifacts pinned to the old mesh — the fused
        # path never dispatches them, and _fwd_bwd stays bound so a
        # later step cannot re-trace over the adopted _mutated_idx
        # routing.  Per-REPLICA error feedback has no exact mapping
        # across a dp change (same rule as _elastic_restore).
        self._persist_pin = None
        self._fused_update = None
        self._residual_vals = None
        self._placed = {}

    def _note_resize_layouts(self):
        """Re-register the observatory ledgers (MXL309/310 inputs,
        HBM census) under the post-resize mesh/layout."""
        from .. import telemetry
        if self.plan is not None:
            from . import planner as _planner
            _planner.note_plan(
                f"spmd:{self.block.name}", self.plan,
                [(p.name, p.data().shape) for p in self._params])
        telemetry.memory.note_param_tree(
            f"spmd:{self.block.name}", self._params, mesh=self.mesh,
            dp_axis=self.dp_axis)
        telemetry.memory.note_opt_state(
            f"spmd:{self.block.name}", self._opt_state_leaves(),
            mesh=self.mesh, dp_axis=self.dp_axis,
            zero_stage=self._zero_stage)

    def _note_resize_probe_base(self):
        """Start-of-step hook while the post-resize probe is armed:
        snapshot the process-global compile counters so the probe's
        delta brackets THIS step only — the window between swap and
        first step is unbounded, and another owner compiling there
        (a serving bucket, a second trainer) must not be attributed
        to the resize (a false MXL503)."""
        from .. import engine
        self._resize_probe_base = engine.compile_counts()

    def _fire_resize_probe(self):
        """End-of-step hook: fire the one-shot post-resize probe (the
        controller's pre-warm-contract accounting) with the
        step-start counter baseline."""
        cb, self._post_resize_probe = self._post_resize_probe, None
        base = getattr(self, "_resize_probe_base", None)
        if cb is not None:
            try:
                cb(base)
            except Exception:
                pass

    # -- public API -------------------------------------------------------
    def step(self, data, label):
        """Run ONE fused SPMD train step; returns the loss NDArray.

        ``data`` may be an NDArray or a tuple of NDArrays; the batch dim is
        sharded over the ``dp`` mesh axis, so callers feed the GLOBAL
        batch (parity note: this replaces ``split_and_load`` + per-device
        forward + kvstore push/pull with one SPMD program).
        """
        import time
        from .. import profiler, telemetry
        with profiler._span("DataParallelTrainer.step",
                            "spmd_step") as sp, \
                telemetry.step_owner(self, "spmd_step"):
            t0 = time.perf_counter()
            loss = self._step_impl(data, label)
            sp.sync(loss._data)
            telemetry.record_step(
                "spmd_step", time.perf_counter() - t0,
                examples=self._global_batch(label), path="spmd")
            return loss

    def step_multi(self, data, label, repeat=None):
        """Run K fused train steps as ONE compiled program.

        ``data``: NDArray or tuple of NDArrays shaped (K, B, ...);
        ``label``: (K, B, ...).  Returns the (K,) per-step losses.
        Alternatively pass SINGLE-batch (B, ...) data with ``repeat=K``
        to run K steps over the same batch without materializing K host
        copies (the batch becomes a plain program input the scanned
        step body reuses — what bench.py's warm-cache bulking needs).

        A ``lax.scan`` over the fused step with params + optimizer
        state as the carry — the XLA rebuild of the reference engine's
        bulked execution (``MXNET_EXEC_BULK_EXEC_TRAIN``): one host
        dispatch amortizes fixed per-step cost (through a remote PJRT
        tunnel that cost is a full RPC round trip, ~30 ms measured)
        over K real optimizer steps.  Per-step RNG keys and per-step
        optimizer scalars (bias-correction t, schedules) are threaded,
        so K scanned steps are numerically the K individual steps.
        Requires ``fuse_step=True`` and no gradient compression.
        """
        import time
        from .. import profiler, telemetry
        with profiler._span("DataParallelTrainer.step_multi",
                            "spmd_step_multi") as sp, \
                telemetry.step_owner(self, "spmd_step_multi"):
            t0 = time.perf_counter()
            loss = self._step_multi_impl(data, label, repeat=repeat)
            sp.sync(loss._data)
            k = int(repeat) if repeat is not None else \
                (label.shape[0] if label.shape else 1)
            per_step = self._global_batch(label) if repeat is not None \
                else (label.shape[1] if len(label.shape) > 1 else 1)
            telemetry.record_step(
                "spmd_step", time.perf_counter() - t0,
                examples=per_step * k, path="spmd_multi", steps=k)
            return loss

    @staticmethod
    def _global_batch(label):
        """Examples per step for throughput accounting (leading dim of
        the global-batch label; 1 for scalar labels)."""
        shape = getattr(label, "shape", ())
        return shape[0] if shape else 1

    @staticmethod
    def _record_poison(e, where):
        """Telemetry for a post-donation failure: event + counter, and
        a flight-recorder artifact so the dispatch/retrace sequence
        that led to the lost training state is preserved."""
        from .. import telemetry
        telemetry.counter(
            "mxtpu_poisons_total",
            "post-donation failures (training state lost)").inc()
        telemetry.record_event("poison", where=where,
                               error=repr(e)[:500])
        telemetry.auto_dump(reason=f"{where}_poisoned")

    def _step_multi_impl(self, data, label, repeat=None):
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from .. import random as _rnd
        from .. import autograd
        from ..ndarray.ndarray import NDArray

        args = list(data) if isinstance(data, (list, tuple)) else [data]
        repeated = repeat is not None
        if repeated:
            k_steps = int(repeat)
            if k_steps <= 0:
                raise MXNetError(
                    f"step_multi: repeat must be positive, got {repeat}")
        else:
            k_steps = args[0].shape[0]
            if label.shape[0] != k_steps:
                raise MXNetError(
                    f"step_multi: label leading dim {label.shape[0]} != "
                    f"data leading dim {k_steps}")
        if not (self._fuse_step and self._rule is not None):
            raise MXNetError("step_multi requires fuse_step=True and "
                             "a fused optimizer rule")
        if self._compression_cfg is not None and not self._zero_stage:
            raise MXNetError("step_multi does not support gradient "
                             "compression (except composed with "
                             "MXTPU_ZERO_STAGE, where the int8 reduce "
                             "rides the ZeRO gradient leg)")

        # single-step views drive setup/tracing (shapes minus K)
        args0 = args if repeated else [a[0] for a in args]
        if self._params is None:
            self._setup(args0)
        self._refresh_health()
        if self._post_resize_probe is not None:
            self._note_resize_probe_base()
        hs = self._health_spec
        health_out = None
        from ..elastic import faults as _faults2
        if _faults2._active and _faults2.nonfinite_due(
                "spmd_step_multi"):
            # poisons the leading element: inner step 0 of a sliced
            # bulk; with repeat= the single shared batch poisons
            # EVERY inner step
            from .. import telemetry as _tm
            args = _tm.health.poison_inputs(args)
        if _faults2._active:
            payload = _faults2.corrupt_due("corrupt_param")
            if payload is not None:
                from ..elastic import integrity as _integrity
                _integrity.corrupt_param_host(self, payload)
        prev = autograd.set_training(True)
        try:
            if self._fwd_bwd is None:
                self._build_fwd_bwd(args0,
                                    label if repeated else label[0])
            if self._full_fn is None:
                if self._zero_stage:
                    self._build_full_step_zero()
                else:
                    self._build_full_step()
            if self._donation_poisoned is not None:
                from .. import engine as _eng
                if _eng._san is not None:
                    _eng._san.note_poisoned_step(
                        self, "spmd_step_multi",
                        self._donation_poisoned)
                raise MXNetError(
                    "this trainer's optimizer state was donated to a "
                    "fused step that failed and is no longer valid; "
                    "call recover(manager) to restore from the last "
                    "committed checkpoint (docs/elasticity.md). "
                    "Original error: "
                    f"{self._donation_poisoned}")

            opt = self.optimizer
            tr_idx = self._tr_idx
            # per-inner-step optimizer scalars from PROSPECTIVE update
            # counts (t+1..t+K) — the real counters only advance after
            # a successful dispatch, so a compile/shape failure cannot
            # silently skew Adam bias correction for later steps
            scal_rows = []
            for k in range(k_steps):
                row = []
                for i in tr_idx:
                    t = opt._index_update_count.get(
                        i, opt.begin_num_update) + k + 1
                    row.extend(np.asarray(sv, dtype=np.float32)
                               for sv in self._rule.scalars(opt, i, t))
                scal_rows.append(np.stack(row) if row
                                 else np.zeros((0,), np.float32))
            scalar_k = jnp.asarray(np.stack(scal_rows))   # (K, S)

            # RNG: snapshot the stream so a pre-dispatch failure can
            # rewind instead of skipping K keys
            ctx0 = args[0].context
            key_snapshot = dict(_rnd._keys)
            keys = [_rnd._next_key_nd(ctx0)._data
                    for _ in range(k_steps)]
            keys_k = jnp.stack(keys)

            batch_k = NamedSharding(
                self.mesh,
                P(self.dp_axis) if repeated else P(None, self.dp_axis))
            used = set()
            x_vals = tuple(self._put_cached(a, batch_k, used)
                           for a in args)
            y_val = self._put_cached(label, batch_k, used)
            self._prune_placed(used)
            param_vals = tuple(p.data()._data for p in self._params)

            kk = (k_steps, repeated)
            fn = self._multi_step_cache.get(kk)
            if fn is None:
                fn = self._build_full_step_multi(k_steps, repeated)
            vals = (param_vals, self._state_vals(), scalar_k, x_vals,
                    y_val, keys_k)
            if hs is not None:
                # per-inner-step sampling flags (K,): gate the
                # in-graph health reductions inside the scan
                from .. import telemetry as _tm
                vals = vals + (jnp.asarray(_tm.health.due_flags(
                    self._health_count, k_steps)),)
                if hs.integrity is not None and hs.integrity.inject:
                    # per-inner-step corruption-ctl rows (K, 4): a
                    # baked drill fires on the exact inner step its
                    # spec selects
                    from ..elastic import integrity as _integrity
                    vals = vals + (jnp.asarray(np.stack(
                        [_integrity.ctl_vector(hs.integrity,
                                               len(tr_idx))
                         for _ in range(k_steps)])),)
            from ..engine import persist as _persist
            if kk not in self._var_avals:
                self._record_variant(
                    f"_k{k_steps}" + ("r" if repeated else ""), vals,
                    k_steps, repeated)
            cached = self._multi_exec.get(kk)
            if cached is None or cached[1] is not fn:
                cached = ({}, fn)
                self._multi_exec[kk] = cached
            sig = _persist.aval_sig(vals)
            call = cached[0].get(sig)
            if call is None:
                suffix = f"_k{k_steps}" + ("r" if repeated else "")
                call = self._tiered_exec(
                    suffix, fn, self._multi_fns[kk], vals, (0, 1))
                cached[0][sig] = call
            from .. import engine
            from ..elastic import faults as _faults
            probe = list(param_vals) + [v for vals in self._state_vals()
                                        for v in vals]

            def _go():
                if _faults._active:
                    _faults.on_dispatch("spmd_step_multi", probe)
                try:
                    return call(*vals)
                except TypeError:
                    # aval drift the AOT executable rejects: demote
                    # THIS signature to the pjit path (cached — not a
                    # raise per step), which absorbs it by retracing
                    # exactly as before the persistent tier existed
                    if call is fn:
                        raise
                    if cached is not None:
                        cached[0][sig] = fn
                    return fn(*vals)

            try:
                out = engine.retrying_call(_go, probe,
                                           "spmd_step_multi")
                if engine._san is not None:
                    # mxsan: params AND state were donated to the
                    # bulked program — shadow-mark the whole probe set
                    engine._san.post_dispatch(
                        "spmd_step_multi", probe, owner=self)
                if hs is not None:
                    loss_k, new_all_params, new_states, health_out = \
                        out
                else:
                    loss_k, new_all_params, new_states = out
            except Exception as e:
                # donate_argnums=(0, 1): if the executable consumed
                # the donated param/state buffers before failing they
                # are gone (same protocol as _step_impl, with params
                # in the blast radius too)
                consumed = any(
                    getattr(v, "is_deleted", lambda: False)()
                    for vals in self._state_vals() for v in vals) or \
                    any(getattr(p.data()._data, "is_deleted",
                                lambda: False)()
                        for p in self._params)
                if not consumed:
                    # trainer still valid: rewind the RNG stream (the
                    # counters never advanced)
                    _rnd._keys.clear()
                    _rnd._keys.update(key_snapshot)
                    raise
                self._donation_poisoned = repr(e)
                self._record_poison(e, "spmd_step_multi")
                raise MXNetError(
                    "bulked train step failed AFTER its param/state "
                    "buffers were donated; the trainer is invalid "
                    "until recover(manager) restores the last "
                    "committed checkpoint (docs/elasticity.md). "
                    f"Original error: {e!r}") from e
            # success: commit the K update-count advances
            for _ in range(k_steps):
                for i in tr_idx:
                    opt._update_count(i)
        finally:
            autograd.set_training(prev)

        for p, v in zip(self._params, new_all_params):
            p.data()._set_data(v)
        self._write_states(new_states)
        if self._post_resize_probe is not None:
            self._fire_resize_probe()
        if hs is not None and health_out is not None:
            from .. import telemetry as _tm
            _tm.health.sample_owner(
                self, f"spmd:{self.block.name}", hs, health_out,
                k_steps)
        return NDArray(loss_k, ctx=args[0].context)

    def _put_cached(self, a, sharding, used):
        """Device-place ``a._data`` under ``sharding`` through the
        trainer's placement cache (skips the device_put when the same
        NDArray/buffer was placed before — ~400 µs/dispatch of host
        overhead otherwise; shared by step and step_multi)."""
        import jax
        import weakref
        v = a._data
        s = getattr(v, "sharding", None)
        if s == sharding:
            return v
        try:
            if s is not None and s.is_equivalent_to(sharding, v.ndim):
                return v
        except (AttributeError, TypeError):
            pass
        used.add(id(a))
        hit = self._placed.get(id(a))
        # the requested sharding is part of the key: step (P(dp)) and
        # step_multi (P(None, dp)) share this cache, and a same-buffer
        # hit under a DIFFERENT sharding must re-place, not silently
        # return the stale placement (ADVICE r3)
        if hit is not None and hit[0]() is a and hit[1] is v \
                and hit[3] == sharding:
            return hit[2]
        out = jax.device_put(v, sharding)
        self._placed[id(a)] = (weakref.ref(a), v, out, sharding)
        return out

    def _prune_placed(self, used):
        if len(self._placed) > len(used):
            self._placed = {k: h for k, h in self._placed.items()
                            if k in used}

    def _build_full_step_multi(self, k_steps, repeated=False):
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import NamedSharding, PartitionSpec as P

        # under ZeRO the scan body is the PER-DEVICE step (the whole
        # scanned program is shard_map-ped below); otherwise the
        # globally-traced step
        zero_on = bool(self._zero_stage)
        full = self._zero_body if zero_on else self._full_fn
        tr_idx = self._tr_idx
        mutated_idx = self._mutated_idx
        has_health = self._health_spec is not None
        _ispec = self._health_spec.integrity if has_health else None
        # a corruption drill adds the per-inner-step ctl rows to the
        # scanned xs (elastic.integrity; production programs carry
        # only the due flags)
        has_ictl = _ispec is not None and _ispec.inject
        # same count _build_full_step derives as n_scalars per param
        n_scal = len(self._rule.scalars(self.optimizer, 0, 1)) \
            * len(tr_idx)

        def full_k(param_vals, tstate_vals, scalar_k, inputs_k,
                   label_k, keys_k, due_k=None, ictl_k=None):
            def body(carry, xs):
                params, tstates = carry
                due = None
                ictl = None
                if repeated:
                    # the batch is a plain program input reused every
                    # inner step — no K host copies, no scanned axis
                    if has_ictl:
                        scal_row, key, due, ictl = xs
                    elif has_health:
                        scal_row, key, due = xs
                    else:
                        scal_row, key = xs
                    inputs, label = inputs_k, label_k
                elif has_ictl:
                    scal_row, inputs, label, key, due, ictl = xs
                elif has_health:
                    scal_row, inputs, label, key, due = xs
                else:
                    scal_row, inputs, label, key = xs
                scal = tuple(scal_row[i] for i in range(n_scal))
                if has_ictl:
                    out = full(params, tstates, scal, inputs, label,
                               key, due, ictl)
                elif has_health:
                    out = full(params, tstates, scal, inputs, label,
                               key, due)
                else:
                    out = full(params, tstates, scal, inputs, label,
                               key)
                if has_health:
                    loss, new_params, new_states, aux, hvec = out
                else:
                    loss, new_params, new_states, aux = out
                params = list(params)
                for j, i in enumerate(tr_idx):
                    params[i] = new_params[j]
                for j, i in enumerate(mutated_idx):
                    params[i] = aux[j]
                ys = (loss, hvec) if has_health else loss
                return (tuple(params), new_states), ys

            if repeated:
                xs = (scalar_k, keys_k)
                if has_health:
                    xs = xs + (due_k,)
                if has_ictl:
                    xs = xs + (ictl_k,)
            else:
                xs = (scalar_k, inputs_k, label_k, keys_k)
                if has_health:
                    xs = xs + (due_k,)
                if has_ictl:
                    xs = xs + (ictl_k,)
            (params_f, tstates_f), ys = lax.scan(
                body, (param_vals, tstate_vals), xs)
            if has_health:
                losses, healths = ys       # healths: (K, n_slots)
                return losses, params_f, tstates_f, healths
            return ys, params_f, tstates_f

        if zero_on:
            # shard_map the whole scanned program: state leaves ride
            # the carry in their (1, chunk) local form, the gradient
            # reduce-scatter + weight all-gather run per inner step
            from ._compat import shard_map
            repl, state_spec, _ = self._zero_specs()
            batch_k = P(self.dp_axis) if repeated \
                else P(None, self.dp_axis)
            out_specs = (repl, repl, state_spec)
            in_specs = (repl, state_spec, repl,
                        batch_k, batch_k, repl)
            if has_health:
                out_specs = out_specs + (repl,)
                in_specs = in_specs + (repl,)   # the due flags
                if has_ictl:
                    in_specs = in_specs + (repl,)   # the ctl rows
            body = shard_map(
                full_k, mesh=self.mesh, in_specs=in_specs,
                out_specs=out_specs, check_vma=False)
            fn = jax.jit(body, donate_argnums=(0, 1))
        else:
            batch_k = NamedSharding(
                self.mesh,
                P(self.dp_axis) if repeated else P(None, self.dp_axis))
            repl = NamedSharding(self.mesh, P())
            param_shardings, state_shardings = self._sharding_tuples()
            # out-shardings pinned for the same TP-safety reason as
            # _build_full_step (weights must not silently re-shard
            # between steps; donation aliasing needs stable layouts)
            out_shardings = (None, param_shardings, state_shardings)
            in_shardings = (param_shardings, state_shardings, None,
                            (batch_k,) * self._n_args, batch_k, repl)
            if has_health:
                out_shardings = out_shardings + (None,)
                in_shardings = in_shardings + (None,)   # the due flags
                if has_ictl:
                    in_shardings = in_shardings + (None,)  # ctl rows
            body = full_k
            fn = jax.jit(
                full_k,
                in_shardings=in_shardings,
                out_shardings=out_shardings,
                donate_argnums=(0, 1))
        self._multi_step_cache[(k_steps, repeated)] = fn
        # the unjitted body backs the persistent tier's abstract
        # re-trace (mutated_idx recovery on a persist hit); under ZeRO
        # that is the shard_map-wrapped scan, traceable at global avals
        self._multi_fns[(k_steps, repeated)] = body
        return fn

    def _sharding_tuples(self, mesh=None, rule=_RULE_UNSET):
        """Param/optimizer-state layouts on ``mesh`` (default: the
        trainer's own), derived STRUCTURALLY — the sharding rule (or
        replication) per param, ``P(dp)`` state rows under ZeRO,
        replication otherwise — never read from live buffers.  This is
        exactly the layout ``_shard_params``/``_elastic_restore``
        place (all three route through
        ``planner.resolve_shardings`` — one resolution path), so for
        the trainer's own mesh it equals the live placements; for a
        resize target mesh it is the layout the pre-warm must pin
        while the live buffers still sit on the OLD mesh (shared by
        the fused single-step and bulked-step builders, and by
        ``prepare_resize``/``apply_resize``).  ``rule`` overrides the
        trainer's own param rule (a plan-targeted resize resolves the
        TARGET plan's rules before the swap adopts them); pass
        ``rule=None`` EXPLICITLY to replicate everything (a rule-free
        target plan) — the unset default falls back to the trainer's
        own rule."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        from . import planner as _planner
        mesh = mesh if mesh is not None else self.mesh
        if rule is _RULE_UNSET:
            rule = self._param_sharding
        params = _planner.resolve_shardings(
            mesh, [(p.name, p.data().shape) for p in self._params],
            rule)
        state_sh = _planner.zero_state_sharding(mesh, self.dp_axis) \
            if self._zero_stage else NamedSharding(mesh, P())
        states = tuple(tuple(state_sh for _ in vals)
                       for vals in self._state_vals())
        return tuple(params), states

    def _step_impl(self, data, label):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from .. import random as _rnd
        from .. import autograd

        args = list(data) if isinstance(data, (list, tuple)) else [data]
        if self._params is None:
            self._setup(args)
        self._refresh_health()
        if self._post_resize_probe is not None:
            self._note_resize_probe_base()
        from ..elastic import faults as _faults
        if _faults._active and _faults.nonfinite_due("spmd_step"):
            # the nonfinite drill: a NaN planted in the batch reaches
            # the loss/gradients through the UNCHANGED compiled
            # program (same shapes — no retrace)
            from .. import telemetry as _tm
            args = _tm.health.poison_inputs(args)
        if _faults._active:
            # the corrupt_param drill: a seeded single-bit flip in ONE
            # device's live param shard (real physical corruption —
            # same shapes, no retrace; the integrity fingerprints see
            # the divergent replica on the next sampled step)
            payload = _faults.corrupt_due("corrupt_param")
            if payload is not None:
                from ..elastic import integrity as _integrity
                _integrity.corrupt_param_host(self, payload)
        if self._fwd_bwd is None:
            prev = autograd.set_training(True)
            try:
                self._build_fwd_bwd(args, label)
            finally:
                autograd.set_training(prev)

        use_full = self._fuse_step and self._rule is not None
        hs = self._health_spec
        health_out = None
        prev = autograd.set_training(True)
        try:
            batch = NamedSharding(self.mesh, P(self.dp_axis))

            used = set()
            x_vals = tuple(self._put_cached(a, batch, used)
                           for a in args)
            y_val = self._put_cached(label, batch, used)
            # only this step's inputs stay pinned — an epoch of
            # distinct batches must not accumulate device copies
            self._prune_placed(used)
            key = _rnd._next_key_nd(args[0].context)

            param_vals = tuple(p.data()._data for p in self._params)
            if use_full:
                opt = self.optimizer
                for i in self._tr_idx:
                    opt._update_count(i)
                scalar_vals = []
                for i in self._tr_idx:
                    t = opt._index_update_count[i]
                    scalar_vals.extend(
                        np.asarray(sv, dtype=np.float32)
                        for sv in self._rule.scalars(opt, i, t))
                # ZeRO subsumes the int8 compressed exchange (the
                # quantized reduce lives on its gradient leg), so the
                # compressed builder/call-shape only applies at stage 0
                compressed = self._compression_cfg is not None and \
                    not self._zero_stage
                if self._full_step is None:
                    if self._zero_stage:
                        self._build_full_step_zero()
                    elif self._compression_cfg is not None:
                        self._build_full_step_compressed()
                    else:
                        self._build_full_step()
                if self._donation_poisoned is not None:
                    from .. import engine as _eng
                    if _eng._san is not None:
                        _eng._san.note_poisoned_step(
                            self, "spmd_step",
                            self._donation_poisoned)
                    raise MXNetError(
                        "this trainer's optimizer state was donated to "
                        "a fused step that failed and is no longer "
                        "valid; call recover(manager) to restore "
                        "parameters/optimizer state from the last "
                        "committed checkpoint (docs/elasticity.md). "
                        f"Original error: {self._donation_poisoned}")
                from .. import engine
                from ..elastic import faults as _faults
                state_flat = [v for vals in self._state_vals()
                              for v in vals]
                # everything _full_donate hands to the executable: the
                # compressed step donates the 2bit error-feedback
                # residuals (argnum 6) alongside the optimizer state,
                # and a plain-SGD run has ONLY residuals as donated
                # state — the poison probe must see them too
                donated_flat = state_flat + (
                    list(self._residual_vals)
                    if compressed and self._residual_vals else [])

                hextra = ()
                if hs is not None:
                    # the dynamic sampling flag (0-d f32): gates the
                    # in-graph health reductions without retracing
                    from .. import telemetry as _tm
                    hextra = (_tm.health.due_flags(
                        self._health_count, 1)[0],)
                    if hs.integrity is not None and \
                            hs.integrity.inject:
                        # the corruption-ctl row a baked drill reads
                        # (all zeros = the XOR block is the identity)
                        from ..elastic import integrity as _integrity
                        hextra = hextra + (_integrity.ctl_vector(
                            hs.integrity, len(self._tr_idx)),)

                if compressed and \
                        not getattr(self, "_wire_noted_c", False):
                    # the compressed path never crosses _tiered_exec,
                    # so it registers with the wire auditor here (once;
                    # program="" — no observatory record to reconcile)
                    self._wire_noted_c = True
                    self._note_wire(
                        "_compressed",
                        getattr(self, "_compressed_fn", None),
                        (param_vals, self._state_vals(),
                         tuple(scalar_vals), x_vals, y_val,
                         key._data, self._residual_vals or ())
                        + hextra, compressed=True, program="")

                def _go():
                    # the fault hook sits INSIDE the retried thunk so
                    # a one-shot "dispatch" fault is absorbed exactly
                    # like a real transient; "dispatch_post" consumes
                    # the donated state first -> poison protocol
                    if _faults._active:
                        _faults.on_dispatch("spmd_full_step",
                                            donated_flat)
                    if compressed:
                        return self._full_step(
                            param_vals, self._state_vals(),
                            tuple(scalar_vals), x_vals, y_val,
                            key._data, self._residual_vals or (),
                            *hextra)
                    return self._dispatch_full(
                        (param_vals, self._state_vals(),
                         tuple(scalar_vals), x_vals, y_val,
                         key._data) + hextra)

                try:
                    out = engine.retrying_call(
                        _go, donated_flat, "spmd_full_step")
                    if engine._san is not None:
                        # mxsan: the donated state set is dead now —
                        # shadow-mark it so a stale reference convicts
                        # with attribution (MXL701)
                        engine._san.post_dispatch(
                            "spmd_full_step", donated_flat, owner=self)
                    if hs is not None:
                        health_out, out = out[-1], out[:-1]
                    if compressed:
                        loss, new_params, new_states, aux, new_res = \
                            out
                        if new_res:
                            self._residual_vals = new_res
                    else:
                        loss, new_params, new_states, aux = out
                except Exception as e:
                    # donate_argnums=(1,): if the executable consumed
                    # the donated state buffers before failing, they
                    # are gone and continuing would silently train on
                    # invalid state (ADVICE r2). Deleted-ness of the
                    # inputs is the ground truth — pre-dispatch errors
                    # (arg binding, tracing, compile) leave the
                    # buffers alive and must NOT brick the trainer.
                    consumed = any(
                        getattr(v, "is_deleted", lambda: False)()
                        for v in donated_flat)
                    if not consumed:
                        raise
                    self._donation_poisoned = repr(e)
                    self._record_poison(e, "spmd_step")
                    raise MXNetError(
                        "fused train step failed AFTER its optimizer "
                        "state was donated; the trainer is invalid "
                        "until recover(manager) restores the last "
                        "committed checkpoint (docs/elasticity.md). "
                        f"Original error: {e!r}") from e
            else:
                loss, grads, aux = self._fwd_bwd(param_vals, x_vals,
                                                 y_val, key._data)
        finally:
            autograd.set_training(prev)

        if use_full:
            for i, v in zip(self._mutated_idx, aux):
                self._params[i].data()._set_data(v)
            for i, v in zip(self._tr_idx, new_params):
                self._params[i].data()._set_data(v)
            self._write_states(new_states)
            if self._post_resize_probe is not None:
                self._fire_resize_probe()
            if hs is not None and health_out is not None:
                from .. import telemetry as _tm
                _tm.health.sample_owner(
                    self, f"spmd:{self.block.name}", hs, health_out, 1)
            return NDArray(loss, ctx=args[0].context)

        # write mutated aux state (BatchNorm running stats) back
        for i, v in zip(self._mutated_idx, aux):
            self._params[i].data()._set_data(v)

        opt = self.optimizer
        if self._rule is not None:
            for i in self._tr_idx:
                opt._update_count(i)
            if self._fused_update is None:
                self._build_fused_update()
            scalar_vals = []
            for i in self._tr_idx:
                t = opt._index_update_count[i]
                scalar_vals.extend(
                    np.asarray(s, dtype=np.float32)
                    for s in self._rule.scalars(opt, i, t))
            from .. import engine as _eng
            _san_hook = _eng._san
            tparam_vals = tuple(
                self._params[i].data()._data for i in self._tr_idx)
            tstate_vals = self._state_vals()
            new_params, new_states = self._fused_update(
                tparam_vals, tstate_vals, grads, tuple(scalar_vals))
            if _san_hook is not None:
                # mxsan: donate_argnums=(0, 1) consumed the params and
                # optimizer state — shadow-mark them so a stale
                # reference convicts with attribution (MXL701); this
                # jit call bypasses the engine seams by design (off
                # cost: the one attribute load above)
                _san_hook.post_dispatch(
                    "spmd_fused_update",
                    tparam_vals + tuple(
                        v for vals in tstate_vals for v in vals),
                    owner=self)
            for i, v in zip(self._tr_idx, new_params):
                self._params[i].data()._set_data(v)
            self._write_states(new_states)
        else:
            # generic fallback: eager fused per-param update ops (still
            # device-side; lr rides as a dynamic scalar, no recompiles;
            # update() does its own _update_count bookkeeping)
            for j, i in enumerate(self._tr_idx):
                p = self._params[i]
                g = NDArray(grads[j], ctx=p.data().context)
                opt.update(i, p.data(), g, self._states[i])
        return NDArray(loss, ctx=args[0].context)
