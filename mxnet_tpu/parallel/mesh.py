"""Device-mesh lifecycle.

The reference's notion of "which devices participate and how" was spread
over kvstore types, ``DMLC_NUM_WORKER`` env vars and comm-tree topology
scans; here it is one object: a named ``jax.sharding.Mesh``.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence

import numpy as np

from ..base import MXNetError

_state = threading.local()


def make_mesh(axes: Dict[str, int], devices=None):
    """Create a named device mesh.

    ``axes`` maps axis name → size, e.g. ``{"dp": 4, "tp": 2}``.  The
    product must not exceed the available device count.  ``devices``
    defaults to ``jax.devices()`` (all chips across all hosts in a
    multi-host run, matching SPMD single-program semantics).
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    n = int(np.prod(list(axes.values())))
    if n > len(devices):
        raise MXNetError(
            f"mesh {axes} needs {n} devices but only {len(devices)} "
            "are available")
    dev_array = np.asarray(devices[:n]).reshape(tuple(axes.values()))
    return Mesh(dev_array, axis_names=tuple(axes.keys()))


def set_mesh(mesh) -> None:
    """Install ``mesh`` as the process-wide default (None clears)."""
    _state.mesh = mesh


def current_mesh():
    """The default mesh, or a fresh 1-axis ``{"dp": all}`` mesh."""
    mesh = getattr(_state, "mesh", None)
    if mesh is None:
        import jax
        mesh = make_mesh({"dp": len(jax.devices())})
        _state.mesh = mesh
    return mesh


def mesh_shape(mesh=None) -> Dict[str, int]:
    mesh = mesh if mesh is not None else current_mesh()
    return dict(zip(mesh.axis_names, mesh.devices.shape))
