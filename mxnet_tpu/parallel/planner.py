"""Unified sharding planner: ONE declarative plan object for every
parallelism decision (ROADMAP item 1; docs/parallelism.md).

ZeRO (PR 10), serving (PR 9), and live resize (PR 11) each grew their
own sharding bookkeeping — every new parallelism feature was becoming
an N^2 pairwise integration.  This module collapses them onto a single
:class:`ShardingPlan`: an ordered list of ``(regex, PartitionSpec)``
rules over the flattened parameter path tree (the
``match_partition_rules`` idiom — SNIPPETS.md [1]), resolved against
ONE named mesh with ``dp``/``tp``/``pp`` (and optionally ``sp``/...)
axes, plus plan-level fields for the ZeRO stage, pipeline stage
assignment, and the serving plane's decode sharding.

Resolution semantics (deliberately boring, so every consumer agrees):

* rules are tried IN ORDER; the first whose regex ``re.search``-matches
  the param path wins;
* scalar / single-element params are never partitioned (rule index
  ``SCALAR``);
* a param matched by NO rule is replicated — silently, which is
  exactly what the MXL313 coverage audit exists to catch
  (``analysis.analyze_parallel``);
* a spec entry is ``None`` (dim not sharded), an axis name, or a tuple
  of axis names (dim sharded over several mesh axes); the empty spec
  ``()`` means fully replicated.

The module also holds THE single definition of the flat ZeRO row
arithmetic (:func:`flat_rows` — ``zero.param_slice`` delegates here)
and of the placement-resolution path every trainer site shares
(:func:`resolve_shardings` — ``_shard_params``, ``_sharding_tuples``
and ``_elastic_restore`` all route through it), so the "two copies of
the layout math drift apart" hazard PR 11 noted is structurally gone.

``elastic.reshard.redistribute_plan`` converts arrays between ANY two
plans (fp32-exact); the warm-start / checkpoint manifests pin a plan's
canonical serialization (:meth:`ShardingPlan.to_record` /
:meth:`struct_hash`) and reject a diverging one naming the exact rule
(:func:`diff_records`).  ``tools/mxplan.py`` renders/diffs/lints plan
files; ``MXTPU_SHARDING_PLAN`` points the trainers at one.
"""
from __future__ import annotations

import hashlib
import json
import re
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..base import MXNetError

__all__ = ["ShardingPlan", "megatron_rules", "plan_from_env",
           "flat_rows", "zero_state_avals", "zero_state_sharding",
           "resolve_shardings", "diff_records", "note_plan", "plans",
           "SCALAR", "WIRE_LEG_KINDS", "WIRE_DTYPES",
           "wire_dtype_itemsize"]

#: rule-index sentinel: the param is scalar/single-element and the
#: planner never partitions it (SNIPPETS.md [1] semantics)
SCALAR = -1

_FORMAT = 1

#: wire-leg kinds a plan-level ``precision`` entry may declare — the
#: taxonomy the wire auditor (``analysis.wire_passes``) classifies
#: every collective into.  ``stats``/``scalar``/``other`` legs exist
#: in the inventory but carry no declarable precision (observability
#: rows and tiny load-bearing reductions are MXL801-exempt).
WIRE_LEG_KINDS = ("dp_grad", "zero_scatter", "zero_gather",
                  "tp_act", "pp", "sp", "decode")

#: canonical wire dtype name -> itemsize, for the plan ``precision``
#: grammar and the MXL801 width comparison.  Names follow numpy/jax
#: canonical spelling (``np.dtype(x).name``); the fp8/bf16 entries are
#: listed explicitly so validation never depends on ml_dtypes import
#: order.
WIRE_DTYPES = {
    "bool": 1, "int8": 1, "uint8": 1,
    "float8_e4m3fn": 1, "float8_e5m2": 1,
    "int16": 2, "uint16": 2, "float16": 2, "bfloat16": 2,
    "int32": 4, "uint32": 4, "float32": 4,
    "int64": 8, "uint64": 8, "float64": 8,
}


def wire_dtype_itemsize(name: str) -> int:
    """Itemsize of one canonical wire dtype name (the ``precision``
    grammar).  Falls back to ``np.dtype`` for spellings like ``f4``
    so hand-written plan JSON is forgiving; raises ``MXNetError`` on
    names neither table knows."""
    name = str(name)
    if name in WIRE_DTYPES:
        return WIRE_DTYPES[name]
    try:
        dt = np.dtype(name)
    except TypeError:
        raise MXNetError(
            f"unknown wire dtype {name!r} (want one of "
            f"{sorted(WIRE_DTYPES)})")
    return int(dt.itemsize)


def _canon_spec(spec) -> tuple:
    """Canonical tuple form of a partition spec: entries are ``None``,
    an axis name, or a tuple of axis names.  Accepts a
    ``jax.sharding.PartitionSpec``, tuple/list, ``None`` (replicated),
    or a single axis name."""
    if spec is None:
        return ()
    if isinstance(spec, str):
        return (spec,)
    out = []
    for e in tuple(spec):
        if e is None or isinstance(e, str):
            out.append(e)
        elif isinstance(e, (tuple, list)) and e and \
                all(isinstance(a, str) for a in e):
            out.append(tuple(e))
        else:
            raise MXNetError(
                f"bad partition-spec entry {e!r} (want None, an axis "
                "name, or a tuple of axis names)")
    while out and out[-1] is None:
        out.pop()              # P('tp', None) == P('tp'): one form
    return tuple(out)


def _spec_axes(spec) -> tuple:
    """Every mesh axis a canonical spec mentions."""
    out = []
    for e in spec:
        if e is None:
            continue
        out.extend(e if isinstance(e, tuple) else (e,))
    return tuple(out)


def _spec_json(spec):
    """JSON form: tuples become lists (round-trips via _canon_spec)."""
    return [list(e) if isinstance(e, tuple) else e for e in spec]


def _partition_spec(spec):
    from jax.sharding import PartitionSpec as P
    return P(*spec)


class ShardingPlan:
    """One declarative parallelism plan: named mesh axes + ordered
    regex partition rules + the plan-level stage/serving fields.

    Args:
      axes: ordered ``{axis_name: size}`` of the named mesh (e.g.
        ``{"dp": 4, "tp": 2}``).  The plan IS the mesh description;
        :meth:`build_mesh` materializes (and memoizes) the
        ``jax.sharding.Mesh``.
      rules: ordered ``[(regex, spec), ...]`` over param paths.  First
        ``re.search`` match wins; specs name only plan axes.
      dp_axis/tp_axis/pp_axis/sp_axis: which axis plays which role
        (consumers read these instead of hard-coding names:
        the trainer's batch axis, megatron rules' tensor axis,
        ``pipeline_apply``'s stage axis, ``ring_attention``'s
        sequence axis).
      zero_stage: the ZeRO stage this plan pins (``None`` defers to
        ``MXTPU_ZERO_STAGE``; 0/1/2 override the env — the plan is the
        single source of truth when present).
      stage_rules: ordered ``[(regex, stage_index), ...]`` pipeline
        stage assignment overrides; params matching none fall back to
        the layer-number layout (``planning._layer_stage``).
      decode: partition spec for the serving plane's KV pages /
        decode batch dim (leading entry shards the slot dim).  ``None``
        = single-chip decode (the pre-plan behavior).
      precision: declared per-leg-kind wire dtype,
        ``{leg_kind: dtype_name}`` over :data:`WIRE_LEG_KINDS` (e.g.
        ``{"dp_grad": "int8"}`` — "grad sync rides the wire
        quantized").  The wire auditor's MXL801 flags any collective
        on a declared leg whose ON-WIRE dtype is WIDER than the
        declaration (the silent fp32-widening class).  ``None`` =
        nothing declared, nothing audited (fail-open, like ``zero``).
    """

    def __init__(self, axes: Dict[str, int],
                 rules: Sequence[Tuple[str, object]] = (),
                 *, dp_axis: str = "dp", tp_axis: str = "tp",
                 pp_axis: str = "pp", sp_axis: str = "sp",
                 zero_stage: Optional[int] = None,
                 stage_rules: Sequence[Tuple[str, int]] = (),
                 decode=None, precision: Optional[Dict[str, str]] = None):
        if not axes:
            raise MXNetError("a plan needs at least one mesh axis")
        self.axes = {}
        for k, v in dict(axes).items():
            k, v = str(k), int(v)
            if v < 1:
                raise MXNetError(f"mesh axis {k!r} has size {v}")
            self.axes[k] = v
        if dp_axis not in self.axes:
            raise MXNetError(
                f"dp_axis {dp_axis!r} is not a plan axis "
                f"{list(self.axes)}")
        self.dp_axis = str(dp_axis)
        self.tp_axis = str(tp_axis)
        self.pp_axis = str(pp_axis)
        self.sp_axis = str(sp_axis)
        if zero_stage is not None and int(zero_stage) not in (0, 1, 2):
            raise MXNetError(
                f"plan zero_stage must be 0, 1, or 2, got {zero_stage}")
        self.zero_stage = None if zero_stage is None else int(zero_stage)
        self.rules: List[Tuple[str, tuple]] = []
        self._compiled: List = []
        for n, entry in enumerate(rules):
            try:
                pattern, spec = entry
            except (TypeError, ValueError):
                raise MXNetError(
                    f"rule #{n} must be a (regex, spec) pair, got "
                    f"{entry!r}")
            pattern = str(pattern)
            try:
                rx = re.compile(pattern)
            except re.error as e:
                raise MXNetError(
                    f"rule #{n} regex {pattern!r} does not compile: {e}")
            spec = _canon_spec(spec)
            for ax in _spec_axes(spec):
                if ax not in self.axes:
                    raise MXNetError(
                        f"rule #{n} ({pattern!r} -> {spec}) names "
                        f"mesh axis {ax!r}, not one of "
                        f"{list(self.axes)}")
            self.rules.append((pattern, spec))
            self._compiled.append(rx)
        self.stage_rules: List[Tuple[str, int]] = []
        self._stage_compiled: List = []
        for n, (pattern, stage) in enumerate(stage_rules):
            pattern, stage = str(pattern), int(stage)
            if not 0 <= stage < self.n_stages:
                raise MXNetError(
                    f"stage rule #{n} assigns stage {stage}, plan has "
                    f"{self.n_stages} pipeline stage(s)")
            try:
                rx = re.compile(pattern)
            except re.error as e:
                raise MXNetError(
                    f"stage rule #{n} regex {pattern!r} does not "
                    f"compile: {e}")
            self.stage_rules.append((pattern, stage))
            self._stage_compiled.append(rx)
        self.decode = None if decode is None else _canon_spec(decode)
        if self.decode is not None:
            for ax in _spec_axes(self.decode):
                if ax not in self.axes:
                    raise MXNetError(
                        f"decode spec {self.decode} names mesh axis "
                        f"{ax!r}, not one of {list(self.axes)}")
        self.precision: Optional[Dict[str, str]] = None
        if precision is not None:
            if not isinstance(precision, dict):
                raise MXNetError(
                    f"plan precision must be a dict of "
                    f"leg_kind -> dtype name, got {precision!r}")
            canon = {}
            for leg, dt in precision.items():
                leg = str(leg)
                if leg not in WIRE_LEG_KINDS:
                    raise MXNetError(
                        f"precision names unknown wire leg {leg!r} "
                        f"(want one of {list(WIRE_LEG_KINDS)})")
                wire_dtype_itemsize(dt)    # validates; raises on junk
                canon[leg] = str(dt)
            self.precision = canon
        self._mesh = None

    # -- mesh -------------------------------------------------------------
    @property
    def n_devices(self) -> int:
        n = 1
        for v in self.axes.values():
            n *= v
        return n

    @property
    def n_stages(self) -> int:
        return int(self.axes.get(self.pp_axis, 1))

    def build_mesh(self, devices=None):
        """The plan's named ``jax.sharding.Mesh`` (memoized: mesh
        identity keys the jit/exec caches, so every consumer of one
        plan must see ONE mesh object)."""
        if self._mesh is None or devices is not None:
            from .mesh import make_mesh
            mesh = make_mesh(dict(self.axes), devices=devices)
            if devices is not None:
                return mesh
            self._mesh = mesh
        return self._mesh

    # -- resolution -------------------------------------------------------
    def match(self, name: str) -> Optional[int]:
        """Index of the first rule whose regex matches ``name`` (None
        = no rule — the param replicates silently)."""
        for i, rx in enumerate(self._compiled):
            if rx.search(name) is not None:
                return i
        return None

    def _entry_fan(self, entry) -> int:
        fan = 1
        for ax in ((entry,) if isinstance(entry, str)
                   else (entry or ())):
            fan *= int(self.axes.get(ax, 1))
        return fan

    def spec_for(self, name: str, shape) -> Tuple[tuple, Optional[int]]:
        """``(canonical spec, rule index)`` for one param path.
        Scalars/single-element tensors resolve replicated with index
        ``SCALAR``; unmatched params resolve replicated with index
        ``None``.  A matched rule whose sharded dim does NOT divide
        its axis fan-out DEMOTES to replication (jax rejects uneven
        shardings at placement — e.g. an odd vocab under a tp-sharded
        embed rule) — the rule index is kept so the MXL313 audit can
        NAME the rule that failed to apply."""
        shape = tuple(int(d) for d in shape)
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return (), SCALAR
        i = self.match(name)
        if i is None:
            return (), None
        spec = self.rules[i][1]
        if len(spec) > len(shape):
            raise MXNetError(
                f"rule #{i} ({self.rules[i][0]!r} -> {spec}) names "
                f"{len(spec)} dims but param {name!r} has shape "
                f"{shape}")
        for d, entry in enumerate(spec):
            if entry is None:
                continue
            if shape[d] % self._entry_fan(entry):
                return (), i          # demoted: layout not honorable
        return spec, i

    def partition_spec(self, name: str, shape):
        """``jax.sharding.PartitionSpec`` for one param, or ``None``
        when the plan replicates it (the ``param_sharding`` calling
        convention)."""
        spec, _i = self.spec_for(name, shape)
        return _partition_spec(spec) if spec else None

    def _spec_shards(self, spec) -> bool:
        return any(self.axes.get(ax, 1) > 1 for ax in _spec_axes(spec))

    def decode_shards(self) -> bool:
        """True when the serving decode spec actually shards on this
        mesh (some named axis has size > 1) — the Server's
        "activate the planned decode layout" gate."""
        return self.decode is not None and \
            self._spec_shards(self.decode)

    def decode_fanout(self) -> int:
        """Device fan-out of the serving decode spec's LEADING entry —
        the slot dim: every bucket's slot count must divide this
        (``serving.Server`` validates at construction AND on
        ``resize_slots``).  1 when no decode spec is set."""
        if not self.decode:
            return 1
        lead = self.decode[0]
        fan = 1
        for ax in ((lead,) if isinstance(lead, str) else (lead or ())):
            fan *= int(self.axes.get(ax, 1))
        return fan

    def param_rule(self) -> Optional[Callable]:
        """A ``(name, shape) -> PartitionSpec | None`` rule for
        ``DataParallelTrainer(param_sharding=...)``.  ``None`` when no
        rule can actually shard anything on this mesh (every spec
        empty, or every named axis has size 1) — the trainer then
        treats the plan as pure data-parallel, which keeps ZeRO
        eligibility exactly as the layout implies."""
        if not any(self._spec_shards(spec) for _p, spec in self.rules):
            return None
        return self.partition_spec

    def resolve(self, named_shapes, dtype_bytes: int = 4):
        """Resolve every ``(name, shape)``: ordered ``{name: row}``
        with ``spec``, ``rule`` (index | SCALAR | None), ``shards``
        (device fan-out of the spec on this mesh), ``nbytes`` (global)
        and ``per_device_bytes``."""
        out = {}
        for name, shape in named_shapes:
            shape = tuple(int(d) for d in shape)
            spec, idx = self.spec_for(name, shape)
            shards = 1
            for ax in _spec_axes(spec):
                shards *= self.axes[ax]
            elems = 1
            for d in shape:
                elems *= d
            nbytes = elems * int(dtype_bytes)
            out[name] = {
                "shape": shape, "spec": spec, "rule": idx,
                "shards": shards, "nbytes": nbytes,
                "per_device_bytes": -(-nbytes // shards),
                # the rule WANTED a sharding the shape cannot honor
                # (non-divisible dim) and resolution replicated instead
                "demoted": bool(idx is not None and idx >= 0 and
                                not spec and self.rules[idx][1]),
            }
        return out

    def stage_of(self, name: str, num_layers: int) -> int:
        """Pipeline stage for one param: explicit ``stage_rules``
        first, then the layer-number layout (decoder layer i goes to
        stage ``i // ceil(L/S)``; embeddings first, head/final norm
        last — ``planning._layer_stage``)."""
        for rx, (_p, stage) in zip(self._stage_compiled,
                                   self.stage_rules):
            if rx.search(name) is not None:
                return stage
        from .planning import _layer_stage
        return _layer_stage(name, num_layers, self.n_stages)

    # -- coverage audit (the MXL313 input) --------------------------------
    def coverage(self, named_shapes, dtype_bytes: int = 4,
                 big_bytes: int = 64 << 20) -> dict:
        """Audit the plan against a param tree.  Returns::

            {"uncovered":      [(name, shape, nbytes), ...],
             "shadowed":       [(rule_idx, pattern, shadowing_idx), ...],
             "replicated_big": [(name, nbytes, rule_idx), ...],
             "demoted":        [(name, shape, rule_idx), ...]}

        * ``uncovered`` — a non-scalar param matched by NO rule
          (silent replication).  Only audited when the plan HAS rules:
          a rule-free plan is the deliberate pure-DP idiom, not a
          coverage gap;
        * ``demoted`` — a matched rule's sharding the shape cannot
          honor (non-divisible dim): the param replicated instead of
          crashing placement, and the rule is named;
        * ``shadowed`` — a rule that some param's name matches, yet an
          EARLIER rule claims every such param: the rule is unreachable
          dead weight (usually an ordering bug);
        * ``replicated_big`` — a tensor of at least ``big_bytes`` the
          resolved plan fully replicates on a >1-device mesh, with the
          responsible rule attributed (``None`` = no rule matched) —
          the MXL309/310 symptom, caught at the rule level.
        """
        named_shapes = [(n, tuple(int(d) for d in s))
                        for n, s in named_shapes]
        res = self.resolve(named_shapes, dtype_bytes=dtype_bytes)
        uncovered = [(n, r["shape"], r["nbytes"])
                     for n, r in res.items()
                     if r["rule"] is None] if self.rules else []
        shadowed = []
        for j, (pattern, _spec) in enumerate(self.rules):
            rx = self._compiled[j]
            # scalar params resolve SCALAR before any regex runs, so
            # they can neither be claimed by a rule nor witness one
            would = [n for n, _s in named_shapes
                     if rx.search(n) is not None and
                     res[n]["rule"] != SCALAR]
            if not would:
                continue            # matches nothing here: just unused
            if all(res[n]["rule"] < j for n in would):
                first = min(res[n]["rule"] for n in would)
                shadowed.append((j, pattern, first))
        replicated_big = []
        if self.n_devices > 1:
            for n, r in res.items():
                if r["rule"] == SCALAR:
                    continue
                if r["nbytes"] >= big_bytes and r["shards"] == 1:
                    replicated_big.append((n, r["nbytes"], r["rule"]))
        demoted = [(n, r["shape"], r["rule"])
                   for n, r in res.items() if r["demoted"]]
        return {"uncovered": uncovered, "shadowed": shadowed,
                "replicated_big": replicated_big, "demoted": demoted}

    # -- canonical serialization (manifest pin) ---------------------------
    def to_record(self) -> dict:
        """Canonical JSON-able form — THE manifest field and the
        struct-hash input.  Stable across processes (no live objects,
        sorted-key JSON)."""
        rec = {
            "format": _FORMAT,
            "axes": [[k, v] for k, v in self.axes.items()],
            "dp_axis": self.dp_axis, "tp_axis": self.tp_axis,
            "pp_axis": self.pp_axis, "sp_axis": self.sp_axis,
            "zero_stage": self.zero_stage,
            "rules": [[p, _spec_json(s)] for p, s in self.rules],
            "stage_rules": [[p, s] for p, s in self.stage_rules],
            "decode": None if self.decode is None
            else _spec_json(self.decode),
        }
        # only-when-set, so every pre-precision plan keeps its exact
        # struct_hash (manifests/warm-starts pin the hash; an absent
        # declaration must not reshuffle them)
        if self.precision is not None:
            rec["precision"] = {k: self.precision[k]
                                for k in sorted(self.precision)}
        return rec

    def to_json(self) -> str:
        return json.dumps(self.to_record(), indent=1, sort_keys=True)

    def struct_hash(self, ignore_sizes: bool = False) -> str:
        """16-hex sha256 over the canonical record — what the persist
        identities and warm-start/checkpoint manifests pin.
        ``ignore_sizes`` zeroes the axis sizes first (the reshard-path
        identity: rules/roles/stage/decode must agree, mesh sizes
        legitimately differ — the same convention as
        ``diff_records(ignore_sizes=True)``)."""
        rec = self.to_record()
        if ignore_sizes:
            rec["axes"] = [[k, 1] for k, _v in rec["axes"]]
        return hashlib.sha256(
            json.dumps(rec, sort_keys=True).encode()).hexdigest()[:16]

    @classmethod
    def from_record(cls, rec) -> "ShardingPlan":
        if not isinstance(rec, dict):
            raise MXNetError(f"malformed plan record: {type(rec)}")
        if rec.get("format") != _FORMAT:
            raise MXNetError(
                f"unsupported plan format {rec.get('format')!r} "
                f"(this build reads format {_FORMAT})")
        try:
            axes = {str(k): int(v) for k, v in rec["axes"]}
            rules = [(p, s) for p, s in rec.get("rules") or ()]
            stage_rules = [(p, int(s))
                           for p, s in rec.get("stage_rules") or ()]
        except (KeyError, TypeError, ValueError) as e:
            raise MXNetError(f"malformed plan record: {e!r}")
        return cls(axes, rules,
                   dp_axis=rec.get("dp_axis", "dp"),
                   tp_axis=rec.get("tp_axis", "tp"),
                   pp_axis=rec.get("pp_axis", "pp"),
                   sp_axis=rec.get("sp_axis", "sp"),
                   zero_stage=rec.get("zero_stage"),
                   stage_rules=stage_rules,
                   decode=rec.get("decode"),
                   # fail-open: a precision-free legacy record loads
                   # with nothing declared (same contract as zero_stage)
                   precision=rec.get("precision"))

    @classmethod
    def from_json(cls, text: str) -> "ShardingPlan":
        try:
            rec = json.loads(text)
        except ValueError as e:
            raise MXNetError(f"malformed plan JSON: {e}")
        return cls.from_record(rec)

    @classmethod
    def load(cls, path: str) -> "ShardingPlan":
        try:
            with open(path) as f:
                text = f.read()
        except OSError as e:
            raise MXNetError(f"cannot read plan {path!r}: {e}")
        return cls.from_json(text)

    def save(self, path: str) -> str:
        import os
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(self.to_json())
        os.replace(tmp, path)
        return path

    # -- plan-to-plan -----------------------------------------------------
    def diff(self, other: "ShardingPlan", named_shapes,
             dtype_bytes: int = 4) -> List[dict]:
        """Per-param reshard report ``self -> other``: what a
        plan-to-plan redistribution would move.  Rows only for params
        whose layout actually changes: ``{name, from_spec, to_spec,
        moves, nbytes}`` (``moves`` from ``elastic.reshard.plan``)."""
        from ..elastic import reshard as _reshard
        a = self.resolve(named_shapes, dtype_bytes=dtype_bytes)
        b = other.resolve(named_shapes, dtype_bytes=dtype_bytes)
        out = []
        for name, ra in a.items():
            rb = b[name]
            moves = _reshard.plan(
                ra["shape"], _partition_spec(ra["spec"]),
                dict(self.axes), _partition_spec(rb["spec"]),
                dict(other.axes))
            if not moves and ra["spec"] == rb["spec"] and \
                    dict(self.axes) == dict(other.axes):
                continue
            out.append({"name": name, "from_spec": ra["spec"],
                        "to_spec": rb["spec"], "moves": moves,
                        "nbytes": ra["nbytes"]})
        return out

    def __eq__(self, other):
        return isinstance(other, ShardingPlan) and \
            self.to_record() == other.to_record()

    def __hash__(self):
        return hash(self.struct_hash())

    def __repr__(self):
        prec = f", precision={self.precision}" if self.precision else ""
        return (f"ShardingPlan(axes={self.axes}, "
                f"{len(self.rules)} rule(s), dp={self.dp_axis!r}, "
                f"zero_stage={self.zero_stage}, "
                f"decode={self.decode}{prec})")


# -- shipped default rule sets ----------------------------------------------

def megatron_rules(tp_axis: str = "tp") -> List[Tuple[str, tuple]]:
    """The shipped megatron row/column rule set for the llama and BERT
    block families (docs/parallelism.md, "Default rule sets").

    Column-parallel (output dim sharded; the next op consumes the
    shard locally): llama q/k/v + gate/up, BERT query/key/value + ffn1
    (weights are ``(out, in)``, so dim 0 shards) and their biases;
    row-parallel (input dim sharded; XLA inserts the psum): llama
    o/down, BERT out/ffn2; vocab-sharded: embedding + untied LM head;
    norms/everything else explicitly replicated by the trailing
    catch-all (full coverage — MXL313 stays quiet)."""
    col = (tp_axis, None)
    row = (None, tp_axis)
    return [
        # llama family (models/llama.py param paths)
        (r"(attn_[qkv]|mlp_(gate|up))_weight$", col),
        (r"(attn_o|mlp_down)_weight$", row),
        # BERT family (models/bert.py param paths)
        (r"(query|key|value|ffn1)_weight$", col),
        (r"(query|key|value|ffn1)_bias$", (tp_axis,)),
        (r"(out|ffn2)_weight$", row),
        # vocab-sharded embedding + untied head (both families)
        (r"(embed|head)_weight$", col),
        # everything else (norms, biases, position embeddings):
        # explicitly replicated, so every param is covered by SOME rule
        (r".", ()),
    ]


# -- env entry point --------------------------------------------------------

def plan_from_env() -> Optional[ShardingPlan]:
    """The plan ``MXTPU_SHARDING_PLAN`` points at (a plan-JSON path),
    or ``None`` when unset.  A malformed file raises loudly — a typo'd
    plan silently training replicated is the failure mode the planner
    exists to kill."""
    from .. import envs
    path = str(envs.get("MXTPU_SHARDING_PLAN") or "").strip()
    if not path:
        return None
    return ShardingPlan.load(path)


# -- THE single resolution / layout definitions -----------------------------

def resolve_plan_axis(plan, mesh, axis: str, role: str):
    """Plan-aware ``(mesh, axis)`` resolution shared by the pipeline
    and ring-attention entry points: a plan supplies BOTH the named
    mesh and the role axis (``role`` is the plan attribute name, e.g.
    ``"pp_axis"``/``"sp_axis"``), so callers stop hard-coding axis
    strings.  ``plan=None`` passes the caller's args through."""
    if plan is None:
        return mesh, axis
    if not isinstance(plan, ShardingPlan):
        raise MXNetError(
            f"plan= must be a parallel.ShardingPlan, got "
            f"{type(plan).__name__}")
    if mesh is None:
        mesh = plan.build_mesh()
    return mesh, getattr(plan, role)


def resolve_shardings(mesh, named_shapes, rule):
    """``[(name, shape)] -> tuple[NamedSharding]`` under ``rule`` (the
    ``(name, shape) -> PartitionSpec | None`` convention; ``None`` rule
    = replicate everything).  This is the ONE placement-resolution
    path: ``DataParallelTrainer._shard_params`` / ``_sharding_tuples``
    / ``_elastic_restore`` and the serving/CLI consumers all call it,
    so "what layout does this param get" has exactly one answer."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    repl = NamedSharding(mesh, P())
    out = []
    for name, shape in named_shapes:
        spec = rule(name, shape) if rule is not None else None
        out.append(NamedSharding(mesh, spec)
                   if spec is not None else repl)
    return tuple(out)


def flat_rows(shape, n_dp: int) -> Tuple[int, int, int]:
    """``(size, padded, chunk)`` of one param's flat ZeRO partition:
    flat length, padded to a multiple of ``n_dp``, per-member slice.
    THE definition — ``zero.param_slice``, ``zero.state_avals`` and
    the resize pre-warm all delegate here (one copy of the arithmetic,
    the drift PR 11 warned about)."""
    size = 1
    for d in shape:
        size *= int(d)
    padded = size + ((-size) % int(n_dp))
    return size, padded, padded // int(n_dp)


def zero_state_avals(shape, n_dp: int, n_leaves: int):
    """Abstract ``(n_dp, chunk)`` f32 optimizer-state rows for one
    param (what a resize pre-warm compiles against before any buffer
    exists)."""
    import jax
    _size, _padded, chunk = flat_rows(shape, n_dp)
    return tuple(jax.ShapeDtypeStruct((int(n_dp), chunk), np.float32)
                 for _ in range(int(n_leaves)))


def zero_state_sharding(mesh, dp_axis: str):
    """The ``P(dp)`` placement of sharded optimizer-state rows —
    shared by state creation, the step builders' pinned shardings and
    the reshard/restore paths (one definition of "where ZeRO rows
    live")."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(mesh, P(dp_axis))


# -- live-plan registry (the MXL313 / mxplan input) -------------------------

_reg_lock = threading.Lock()
_plans: Dict[str, dict] = {}


def note_plan(owner: str, plan: ShardingPlan, named_shapes,
              dtype_bytes: int = 4) -> None:
    """Register a live consumer's plan + param tree for the coverage
    audit (``analysis.analyze_parallel`` — MXL313) and
    ``tools/mxplan.py``.  Never raises (telemetry-grade)."""
    try:
        with _reg_lock:
            _plans[str(owner)] = {
                "plan": plan,
                "named_shapes": [(str(n), tuple(int(d) for d in s))
                                 for n, s in named_shapes],
                "dtype_bytes": int(dtype_bytes),
            }
    except Exception:
        pass


def plans() -> Dict[str, dict]:
    """Registered live plans (copies)."""
    with _reg_lock:
        return {k: dict(v) for k, v in _plans.items()}


def _reset():
    """Test hook."""
    with _reg_lock:
        _plans.clear()


# -- manifest comparison ----------------------------------------------------

def _rule_str(entry) -> str:
    p, s = entry[0], entry[1]
    return f"{p!r} -> {tuple(s) if isinstance(s, list) else s}"


def diff_records(a, b, ignore_sizes: bool = False) -> Optional[str]:
    """Compare two canonical plan records (dicts from
    :meth:`ShardingPlan.to_record`, or ``None``).  Returns ``None``
    when equivalent, else a one-line reason NAMING the diverging rule
    or field — the fail-open warm-start/manifest reject message.
    ``ignore_sizes`` compares axis NAMES but not sizes (the reshard
    warm-start path, where a mesh-size change is legitimate)."""
    if a is None and b is None:
        return None
    if (a is None) != (b is None):
        return ("one side has a sharding plan and the other does not "
                f"(manifest: {'set' if a else 'unset'}, current: "
                f"{'set' if b else 'unset'})")
    ra = [tuple(r) for r in a.get("rules") or ()]
    rb = [tuple(r) for r in b.get("rules") or ()]
    for i, (ea, eb) in enumerate(zip(ra, rb)):
        if list(ea[1] or []) != list(eb[1] or []) or ea[0] != eb[0]:
            return (f"rule #{i} diverges: manifest {_rule_str(ea)} vs "
                    f"current {_rule_str(eb)}")
    if len(ra) != len(rb):
        longer, which = (ra, "manifest") if len(ra) > len(rb) \
            else (rb, "current")
        i = min(len(ra), len(rb))
        return (f"rule #{i} exists only in the {which} plan: "
                f"{_rule_str(longer[i])}")
    axes_a = [[k, 1 if ignore_sizes else v]
              for k, v in a.get("axes") or ()]
    axes_b = [[k, 1 if ignore_sizes else v]
              for k, v in b.get("axes") or ()]
    if axes_a != axes_b:
        return (f"mesh axes diverge: manifest {a.get('axes')} vs "
                f"current {b.get('axes')}")
    for field in ("dp_axis", "tp_axis", "pp_axis", "sp_axis",
                  "zero_stage", "stage_rules", "decode", "precision"):
        if a.get(field) != b.get(field):
            return (f"plan field {field!r} diverges: manifest "
                    f"{a.get(field)!r} vs current {b.get(field)!r}")
    return None
