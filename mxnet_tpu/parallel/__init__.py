"""TPU-native parallelism: device meshes, collectives, sharded training.

This package is the rebuild's answer to the reference's entire distributed
stack (SURVEY.md §2.3): kvstore device comm (``comm.h``/``comm_tree.h``),
NCCL (``kvstore_nccl.h``), ps-lite parameter servers (``kvstore_dist*.h``)
and the manual model-parallel ``ctx_group`` mechanism — all replaced by one
idiom: lay the devices out in a named :class:`jax.sharding.Mesh`, annotate
array shardings, and let XLA insert the collectives over ICI/DCN.

Public surface:

* :func:`make_mesh` / :func:`set_mesh` / :func:`current_mesh` — mesh
  lifecycle.  Axis names are free-form; the conventional ones are ``dp``
  (data), ``tp`` (tensor), ``pp`` (pipeline), ``sp`` (sequence/context),
  ``ep`` (expert).
* :mod:`~mxnet_tpu.parallel.collectives` — ``psum``/``all_gather``/
  ``ppermute``/``all_to_all`` wrappers for use inside ``shard_map``-ped
  code (Pallas ring kernels use the same axis names).
* :class:`DataParallelTrainer` — one-jit SPMD training step over a mesh:
  batch sharded on ``dp``, params replicated (or TP-sharded via a rule),
  optimizer running on-chip.  This is the TPU-native fast path that the
  kvstore facade's push/pull semantics compile down to.
"""
from .mesh import make_mesh, set_mesh, current_mesh, mesh_shape
from . import collectives
from . import planner
from . import zero
from .planner import ShardingPlan, megatron_rules
from .collectives import (quantized_psum, quantized_reduce_scatter,
                          reduce_scatter, vocab_parallel_softmax_ce)
from .trainer import DataParallelTrainer
from .ring_attention import ring_attention, ring_attention_sharded
from .pipeline import pipeline_apply, pipeline_value_and_grad
from .planning import llama_param_rule, sharding_plan


def moe_param_rule(ep_axis="ep", inner=None):
    """Param-sharding rule for expert-parallel MoE: expert tensors
    (named expert_*) shard their leading E dim over ``ep_axis``; under
    the mesh-jitted trainer step GSPMD then inserts the dispatch/return
    all-to-alls (the canonical GShard lowering).  Compose with a
    tensor-parallel rule via ``inner``."""
    from jax.sharding import PartitionSpec as P

    def rule(name, shape):
        if "expert_" in name and len(shape) >= 2:
            return P(ep_axis, *([None] * (len(shape) - 1)))
        return inner(name, shape) if inner is not None else None

    return rule

__all__ = ["vocab_parallel_softmax_ce",
           "moe_param_rule", "pipeline_apply",
           "pipeline_value_and_grad",
           "make_mesh", "set_mesh", "current_mesh", "mesh_shape",
           "collectives", "planner", "zero", "ShardingPlan",
           "megatron_rules", "DataParallelTrainer",
           "quantized_psum", "quantized_reduce_scatter",
           "reduce_scatter", "ring_attention",
           "ring_attention_sharded", "llama_param_rule",
           "sharding_plan"]
