"""Build/packaging (parity: reference python/setup.py + root Makefile
feature-flag build — SURVEY.md §2.6 "Build system").

Installs the ``mxnet_tpu`` package and compiles the native runtime
``libmxtpu.so`` from ``src/`` as part of ``build_py`` (the library is
also auto-built on first import when a toolchain is present, so a plain
checkout works without installing).

    pip install -e .            # editable, with native build
    MXTPU_SKIP_NATIVE=1 pip install .   # pure-Python fallback paths
"""
import os
import subprocess

from setuptools import setup, find_packages
from setuptools.command.build_py import build_py as _build_py

HERE = os.path.dirname(os.path.abspath(__file__))


class build_py(_build_py):
    def run(self):
        if not os.environ.get("MXTPU_SKIP_NATIVE"):
            try:
                subprocess.run(["make", "-C",
                                os.path.join(HERE, "src")], check=True)
            except Exception as e:  # degrade like _native.available()
                print(f"warning: native build failed ({e}); "
                      "pure-Python fallbacks will be used")
        super().run()


setup(
    name="mxnet_tpu",
    version="0.2.0",
    description=("TPU-native deep-learning framework with MXNet's "
                 "capabilities (JAX/XLA/Pallas compute, C++ runtime)"),
    packages=find_packages(include=["mxnet_tpu", "mxnet_tpu.*"]),
    package_data={"mxnet_tpu": ["lib/libmxtpu.so",
                                "lib/libmxtpu_image.so",
                                "lib/libmxtpu_pjrt.so"]},
    python_requires=">=3.10",
    install_requires=["numpy", "jax"],
    extras_require={"checkpoint": ["orbax-checkpoint"]},
    cmdclass={"build_py": build_py},
    scripts=["tools/launch.py", "tools/im2rec.py"],
)
